"""tmlive — whole-program static liveness & boundedness proof for the
serving path.

ROADMAP's north star is a node serving heavy traffic from millions of
users. The two failure modes that actually kill such a node under load
— a *stall* (a blocking call on the event loop or under a hot lock)
and an *OOM* (a shared container that only grows) — were guarded only
by runtime sampling (lockwatch's 0.25 s hold budget sees executed
paths) and by convention. tmlive turns both into machine-checked
tier-1 gates over the PR-5 call graph and PR-6 thread roots:

1. **Blocking catalog + reachability** (`blockcat.py`): a reviewed
   catalog of blocking primitives (socket verbs, fsync/flush,
   subprocess, `time.sleep`, `Lock.acquire`/`Queue.get`/`Event.wait`/
   `join` with and without timeouts, device sync points), each call
   site classified bounded/unbounded through the same from-import/
   alias machinery tmcheck uses — `from time import sleep as nap`
   cannot evade it.
2. **`live-block-under-lock`** (`holdflow.py`): tmrace's MUST-held
   lockset propagated to every blocking site; an unbounded site under
   a named lock is flagged with the full witness (lock class, call
   path, primitive). Turns lockwatch's sampled hold budget into a
   proof over all paths, and backs the runtime cross-check: every
   witnessed hold-budget overrun must be statically explained.
3. **`live-block-in-main-loop`** / **`live-unbounded-blocking`**
   (`loopflow.py`): no unbounded blocking call reachable from the
   asyncio `main-loop` identity without an executor hop — the static
   form of "the serving path never stalls on disk, peer, or device";
   spawned-thread residual sites form the review-and-annotate family.
4. **`live-grow-unbounded`** (`growth.py`): every shared container a
   rooted function grows must be provably bounded — ring
   (deque maxlen), rotation/eviction/reset recognized structurally, or
   a reviewed `# tmlive: bounded=<reason>` annotation.

Suppressions (same comment-block-above convention as the rest of the
family): `# tmlive: block-ok — why` for the blocking rules,
`# tmlive: grow-ok — why` for a grow site, `# tmlive:
bounded=<reason>` on a container birth or grow line. Counted
fingerprint baseline `live_baseline.json` ships (and is pinned) EMPTY.
Run via `scripts/lint.py --live` (in the default full gate); tier-1
tests in tests/test_tmlive.py; docs/static_analysis.md has the
catalog, the boundedness idioms, and the static-vs-lockwatch division
of labor.
"""

from __future__ import annotations

import os
import re
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ..tmlint import (
    Violation,
    load_baseline,
    new_violations,
    save_baseline,
)
from ..tmcheck.callgraph import Package, build_package
from ..tmrace import threadroots
from ..tmrace.lockset import FuncSummary, Summarizer, propagate
from ..tmrace.threadroots import discover_roots, reach
from . import blockcat, growth, holdflow, loopflow
from .blockcat import HARNESS_PREFIXES, UNBOUNDED, collect_sites
from .holdflow import crosscheck_overruns  # re-export (conftest/tests)

__all__ = [
    "RULES",
    "LIVE_BASELINE_PATH",
    "LIVE_BASELINE_NOTE",
    "LiveReport",
    "analyze",
    "live_violations",
    "new_live_violations",
    "update_live_baseline",
    "crosscheck_overruns",
]

FuncKey = Tuple[str, str]

LIVE_BASELINE_PATH = os.path.join(
    os.path.dirname(__file__), "live_baseline.json"
)

LIVE_BASELINE_NOTE = (
    "Accepted pre-existing liveness/boundedness findings, fingerprinted "
    "by rule:path:sha1(source_line)[:12]. New findings are anything "
    "over these counts. Do not hand-edit counts to sneak a finding in "
    "— fix it, or suppress it with a justified '# tmlive: block-ok — "
    "why' / '# tmlive: grow-ok — why' / '# tmlive: bounded=<reason>'."
)

RULES = [
    (
        "live-block-under-lock",
        "unbounded blocking primitive reachable while a named lock is "
        "held (MUST-held lockset over all static paths)",
    ),
    (
        "live-block-in-main-loop",
        "unbounded blocking primitive reachable from the asyncio "
        "main-loop identity without an executor hop",
    ),
    (
        "live-unbounded-blocking",
        "unbounded blocking primitive on a spawned thread: reviewed "
        "residual — fix it or write down why blocking is that "
        "thread's job",
    ),
    (
        "live-grow-unbounded",
        "shared container grown from the serving path with no "
        "boundedness proof (ring / rotation / eviction / reviewed "
        "bounded= annotation)",
    ),
]

_BLOCK_OK_RE = re.compile(r"#\s*tmlive:\s*block-ok\b")
_GROW_OK_RE = re.compile(r"#\s*tmlive:\s*grow-ok\b")
_BOUNDED_RE = re.compile(r"#\s*tmlive:\s*bounded=([^#]+?)\s*(?:#|$)")


def suppression_maps(lines: List[str]):
    """(block_ok, grow_ok, bounded): line-number sets/maps for the
    three tmlive annotations, with the comment-block-above convention
    implemented once in tmlint.comment_cover_lines (shared with
    tmlint/tmcheck/tmrace so the analyzers can never drift on what a
    suppression comment reaches)."""
    from ..tmlint import comment_cover_lines

    block_ok: Set[int] = set()
    grow_ok: Set[int] = set()
    bounded: Dict[int, str] = {}
    for i, text in enumerate(lines, start=1):
        if _BLOCK_OK_RE.search(text):
            block_ok.update(comment_cover_lines(lines, i, text))
        if _GROW_OK_RE.search(text):
            grow_ok.update(comment_cover_lines(lines, i, text))
        m = _BOUNDED_RE.search(text)
        if m:
            for ln in comment_cover_lines(lines, i, text):
                bounded.setdefault(ln, m.group(1).strip())
    return block_ok, grow_ok, bounded


class LiveReport:
    """Everything one analyze() run produced."""

    def __init__(self) -> None:
        self.sites: List[blockcat.BlockSite] = []
        self.containers: Dict[tuple, growth.Container] = {}
        self.identities: Dict[FuncKey, Set[str]] = {}
        self.violations: List[Violation] = []
        # lock names (static identity) with a flagged blocking site
        self.flagged_locks: Set[str] = set()
        # lock names with a statically-KNOWN blocking site that is not
        # a finding: a suppressed unbounded site, or a BOUNDED site
        # (wait(0.5) under a lock is green here — lockwatch owns
        # "bounded but too long" — but its overrun is still explained
        # by this set, not by an OVERRUN_OK "pure memory ops" claim
        # that would then be false)
        self.suppressed_locks: Set[str] = set()
        self.stats: Dict[str, int] = {}


def analyze(
    pkg: Optional[Package] = None,
    include_test_roots: bool = False,
) -> LiveReport:
    pkg = pkg or build_package()
    report = LiveReport()

    # -- roots: the serving path's concurrent entry points (package
    # roots only by default; the tests/ hammers drive the package from
    # pytest, not from a serving node) --
    roots = discover_roots(pkg)
    if include_test_roots:
        roots += threadroots.discover_test_roots(pkg)
    while True:
        extra = threadroots.callback_roots(pkg, roots)
        if not extra:
            break
        roots += extra
    identities, parents = reach(pkg, roots)
    report.identities = identities

    # -- locksets (tmrace's machinery, MUST direction) --
    summarizer = Summarizer(pkg)
    summaries: Dict[FuncKey, FuncSummary] = {}
    for key in identities:
        summaries[key] = summarizer.summarize_function(pkg.functions[key])
    root_keys = sorted({r.key for r in roots})
    entry_contexts, _edges, _trunc = propagate(pkg, summaries, root_keys)

    # -- suppression maps --
    block_ok: Dict[str, Set[int]] = {}
    grow_ok: Dict[str, Set[int]] = {}
    bounded_ann: Dict[str, Dict[int, str]] = {}
    for path, mod in pkg.modules.items():
        b, g, ba = suppression_maps(mod.lines)
        block_ok[path] = b
        grow_ok[path] = g
        bounded_ann[path] = ba

    def _line_text(path: str, lineno: int) -> str:
        lines = pkg.modules[path].lines
        if 1 <= lineno <= len(lines):
            return lines[lineno - 1].strip()
        return ""

    violations: List[Violation] = []

    # -- blocking rules --
    sites = collect_sites(pkg)
    report.sites = sites
    n_bounded = n_unbounded = n_unreachable = n_suppressed = 0
    for site in sites:
        in_harness = site.path.startswith(HARNESS_PREFIXES)
        summary = summaries.get(site.key)
        locks: FrozenSet[str] = frozenset()
        if summary is not None:
            locks = holdflow.site_locks(
                summary, entry_contexts, site.key, site.lineno, site.col
            )
        named = holdflow.named_locks(locks)
        if site.kind != UNBOUNDED:
            n_bounded += 1
            if site.kind == blockcat.BOUNDED and not in_harness:
                # a bounded wait under a named lock is not a finding,
                # but a hold-budget overrun on that lock is explained
                # by it — record for the lockwatch cross-check. A
                # NONBLOCKING site (get_nowait, acquire(False)) cannot
                # stall and must NOT explain anything.
                report.suppressed_locks.update(named)
            continue
        n_unbounded += 1
        if in_harness:
            continue
        rule = loopflow.pick_rule(identities, site.key, bool(named))
        if rule is None:
            n_unreachable += 1
            continue
        if site.lineno in block_ok.get(site.path, ()):
            n_suppressed += 1
            report.suppressed_locks.update(named)
            continue
        report.flagged_locks.update(named)
        witness = loopflow.main_witness(pkg, parents, identities, site.key)
        if rule == "live-block-under-lock":
            detail = (
                f"holds {holdflow.describe_locks(named)} across "
                f"{site.primitive} ({site.detail})"
            )
        elif rule == "live-block-in-main-loop":
            detail = (
                f"{site.primitive} ({site.detail}) reachable from the "
                "asyncio main-loop identity — one call stalls every "
                "handler, subscriber and vote in flight"
            )
        else:
            detail = (
                f"{site.primitive} ({site.detail}) on a spawned "
                "thread: fix it or write down why blocking is this "
                "thread's job"
            )
        violations.append(
            Violation(
                rule=rule,
                path=site.path,
                line=site.lineno,
                col=site.col,
                message=detail + (f"; witness: {witness}" if witness else ""),
                source=_line_text(site.path, site.lineno),
            )
        )

    # -- growth rule --
    containers = growth.collect_growth(pkg, summarizer.attribution)
    report.containers = containers
    n_growers = n_bounded_containers = 0
    for var, c in sorted(containers.items(), key=lambda kv: str(kv[0])):
        rooted_grows = [g for g in c.grows if g.key in identities]
        if not rooted_grows:
            continue
        n_growers += 1
        reason = bounded_ann.get(c.path, {}).get(c.lineno)
        if reason:
            c.bounded_reason = reason
        if c.ring:
            c.bounded_reason = c.bounded_reason or "ring (deque maxlen)"
        elif c.shrinks:
            c.bounded_reason = c.bounded_reason or (
                "rotation/eviction/reset present"
            )
        if c.bounded_reason:
            n_bounded_containers += 1
            continue
        for g in rooted_grows:
            site_reason = bounded_ann.get(g.path, {}).get(g.lineno)
            if site_reason or g.lineno in grow_ok.get(g.path, ()):
                n_suppressed += 1
                continue
            ids = sorted(identities.get(g.key, set()))[:3]
            violations.append(
                Violation(
                    rule="live-grow-unbounded",
                    path=g.path,
                    line=g.lineno,
                    col=g.col,
                    message=(
                        f"{c.render_var()} grows via {g.what} on the "
                        f"serving path (roots: {', '.join(ids)}) with no "
                        "boundedness proof — no ring, no eviction/reset "
                        "site, no `# tmlive: bounded=` annotation: an "
                        "OOM at serving scale"
                    ),
                    source=_line_text(g.path, g.lineno),
                )
            )

    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    report.violations = violations
    report.stats = {
        "sites_total": len(sites),
        "sites_bounded": n_bounded,
        "sites_unbounded": n_unbounded,
        "sites_unreachable": n_unreachable,
        "suppressed": n_suppressed,
        "containers": len(containers),
        "containers_growing": n_growers,
        "containers_bounded": n_bounded_containers,
        "roots": len(roots),
    }
    return report


def live_violations(
    pkg: Optional[Package] = None, **kwargs
) -> List[Violation]:
    return analyze(pkg, **kwargs).violations


def new_live_violations(
    pkg: Optional[Package] = None,
    baseline_path: Optional[str] = None,
    **kwargs,
) -> List[Violation]:
    violations = live_violations(pkg, **kwargs)
    baseline = load_baseline(baseline_path or LIVE_BASELINE_PATH)
    return new_violations(violations, baseline)


def update_live_baseline(
    pkg: Optional[Package] = None,
    baseline_path: Optional[str] = None,
    **kwargs,
) -> Dict[str, int]:
    return save_baseline(
        live_violations(pkg, **kwargs),
        baseline_path or LIVE_BASELINE_PATH,
        note=LIVE_BASELINE_NOTE,
    )
