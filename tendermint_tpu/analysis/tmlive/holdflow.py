"""`live-block-under-lock` — no unbounded blocking call while a lock
is held, proven over all static paths.

lockwatch's hold-time budget (TM_TPU_LOCKWATCH_BUDGET_S, 0.25 s)
watches the paths the suite happens to execute; this rule is the proof
over ALL paths: tmrace's MUST-held lockset machinery is propagated to
every blocking site blockcat catalogs, and any *unbounded* site whose
lockset contains a named lock is flagged with the full witness — lock
class, shortest call path from a thread root, and the blocking
primitive. A bounded site (a `wait(0.1)`, a constant sleep) under a
lock is recorded in stats but not flagged: lockwatch's runtime budget
owns the "bounded but too long" half.

The lockset at a site is the same three-part union tmrace uses, all
MUST-direction (never a false "held"):

- locks syntactically held at the call (`with lock:` enclosure);
- the function's MUST-entry lockset (intersection over every explored
  call path from every thread root);
- the `*_locked` naming convention.

A WILDCARD lock (one the analysis cannot name) does NOT trigger the
rule — an audited-unknowable guard should not conjure findings — but
named locks always do, ranked or not; the message names the lockwatch
RANK entry when one exists, because a ranked lock is by definition on
the crypto hot path where a stall is a serving outage.

## The lockwatch cross-check (`crosscheck_overruns`)

Runtime hold-budget overruns are promoted from warnings to a
structured record (lockwatch.HOLD_LOG); every witnessed overrun must
be *explained*: either tmlive flagged (or carries a suppression for) a
blocking site under that lock class, or the lock appears in
OVERRUN_OK below — the reviewed list of locks whose critical sections
are pure memory operations, where an overrun can only mean the host
scheduler parked the holder (a loaded CI box), not that the code
blocks. That list is itself backed by this rule: if someone adds a
blocking call under one of these locks, the static gate goes red
before the runtime budget ever fires.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from ..tmrace.lockorder import STATIC_RANK_NAMES
from ..tmrace.lockset import WILDCARD

__all__ = ["OVERRUN_OK", "site_locks", "named_locks", "crosscheck_overruns"]

FuncKey = Tuple[str, str]

# lockwatch rank name -> why a hold-budget overrun on it is scheduler
# noise, not a blocking call. Every entry is a claim tmlive's
# block-under-lock gate machine-checks on each run: the moment a
# blocking call becomes reachable under one of these locks, the static
# gate fails and the entry must be removed.
OVERRUN_OK: Dict[str, str] = {
    "breaker.registry": (
        "registry get/pop + CircuitBreaker construction; pure memory "
        "ops — tmlive proves no blocking call is reachable under it"
    ),
    "breaker.instance": (
        "state-machine transitions and gauge publishes; the probe fn "
        "runs OUTSIDE the lock by design (tmlive-proven)"
    ),
    "sigcache.rotate": (
        "set rotation/promotion; pure memory ops on bounded "
        "generations"
    ),
    "trace.ring": (
        "ring replacement/snapshot only (appends are lock-free); "
        "bounded copies of a bounded deque"
    ),
    "tpu_verifier.wedged": (
        "counter/free-list bookkeeping around the watchdog handshake; "
        "the gather itself runs outside the lock"
    ),
    "metrics.metric": "counter/gauge/histogram arithmetic only",
    "metrics.registry": "name-table insert/lookup only",
}


def site_locks(
    summary,
    entry_contexts: Dict[FuncKey, List[FrozenSet[str]]],
    key: FuncKey,
    lineno: int,
    col: int,
) -> FrozenSet[str]:
    """MUST-held lockset at one call position inside `key`."""
    ctxs = entry_contexts.get(key)
    must_entry: FrozenSet[str] = (
        frozenset.intersection(*ctxs) if ctxs else frozenset()
    )
    syntactic = summary.call_locks.get((lineno, col), frozenset())
    return syntactic | must_entry | summary.convention


def named_locks(locks: Iterable[str]) -> List[str]:
    """The flaggable subset: everything but the wildcard."""
    return sorted(l for l in locks if l != WILDCARD)


def rank_name(lock: str) -> Optional[str]:
    return STATIC_RANK_NAMES.get(lock)


def describe_locks(locks: List[str]) -> str:
    out = []
    for l in locks:
        rn = rank_name(l)
        out.append(f"{l} (rank {rn})" if rn else l)
    return ", ".join(out)


# ---------------------------------------------------------------------------
# runtime cross-check


def _static_names(rank: str) -> Set[str]:
    return {s for s, r in STATIC_RANK_NAMES.items() if r == rank}


def crosscheck_overruns(
    long_holds: Iterable[dict],
    flagged_locks: Set[str],
    suppressed_locks: Set[str],
    overrun_ok: Optional[Dict[str, str]] = None,
) -> List[dict]:
    """Witnessed hold-budget overruns with NO explanation: the lock is
    neither statically flagged/suppressed as holding over a blocking
    call (so the overrun is the known, reviewed stall) nor in
    OVERRUN_OK (so it cannot be dismissed as scheduler noise). Each
    returned entry is the original overrun record plus a `why` telling
    the operator what would explain it."""
    overrun_ok = OVERRUN_OK if overrun_ok is None else overrun_ok
    unexplained: List[dict] = []
    for h in long_holds:
        name = h.get("name", "")
        if name in overrun_ok:
            continue
        statics = _static_names(name) or {name}
        if statics & (flagged_locks | suppressed_locks):
            continue
        unexplained.append(
            {
                **h,
                "why": (
                    f"lock {name!r} overran the hold budget but tmlive "
                    "knows no blocking site under it and OVERRUN_OK has "
                    "no scheduler-noise rationale for it — add the "
                    "blocking call to the catalog, suppress the site "
                    "with a reason, or extend OVERRUN_OK"
                ),
            }
        )
    return unexplained
