"""Static wire-schema extraction + conformance checking.

The hand codec (`encoding/proto.py` + per-type `to_proto`/`from_proto`)
replaces ~33k LoC of generated gogo-proto; its field numbers, wire
types, and emission order ARE the protocol. This module recovers that
schema statically — no imports, no execution — from every encoder/
decoder in the codec-bearing modules, checks it for internal
consistency, and diffs it against the checked-in golden table
(`analysis/tmcheck/schema.json`, derived from the reference .proto
files; each entry records which reference message it mirrors).

Per message the extractor recovers, from the encoder:
    [ {tag, method, wire, repeated, conditional} ... ]  in emission order
(`repeated`: the write sits in a loop; `conditional`: under an `if` —
mutually-exclusive oneof arms and nullable submessages), and from the
decoder the set of parsed tags. Three checks:

- **schema-drift** — extracted encoder schema differs from the golden
  table (tag, wire type, writer method, order, flags) or a message
  appeared/disappeared. Canonical bytes changed ⇒ tier-1 failure; the
  reviewed update path is `scripts/lint.py --schema-update`.
- **schema-order** — a writer emits a higher tag before a lower one on
  one control-flow path (ProtoWriter would raise at runtime; this
  catches it before any test constructs the message). Writes in
  disjoint branches of one `if`/`elif` chain are exempt (oneofs).
- **schema-symmetry** — a tag written but never parsed (or parsed but
  never written) by the paired decoder. Deliberate asymmetries are
  annotated in-source: `# tmcheck: unparsed=N — why` inside the
  encoder/decoder pair's bodies (e.g. ValidatorSet total_voting_power
  is recomputed, not trusted from the wire), `# tmcheck: unwritten=N
  — why` for read-only legacy tags.

Encoder recognition: a function in a scoped module that instantiates
`ProtoWriter()` and whose name is `to_proto`/`to_proto_bytes`/
`encode_*`/`_enc_*`/one of the canonical sign-bytes builders. Only the
*primary* writer's fields (the one whose `.finish()` is returned) form
the message; nested inline writers are separate messages only when
they live in their own function (the codebase's dominant idiom).
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..tmlint import Violation, dotted_name, iter_py_files, package_root

__all__ = [
    "GOLDEN_PATH",
    "SCHEMA_SCOPE_PREFIXES",
    "SCHEMA_SCOPE_FILES",
    "extract_module",
    "extract_package",
    "load_golden",
    "save_golden",
    "schema_violations",
    "check_package_schema",
]

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "schema.json")

# the codec-bearing layers the extractor indexes
SCHEMA_SCOPE_PREFIXES = ("types/",)
SCHEMA_SCOPE_FILES = {
    "abci/codec.py",
    "consensus/msgs.py",
    "blocksync/msgs.py",
    "statesync/msgs.py",
    "mempool/reactor.py",
    "evidence/reactor.py",
    "p2p/types.py",
    "crypto/keys.py",
    "crypto/merkle.py",
}


def in_schema_scope(path: str) -> bool:
    return path in SCHEMA_SCOPE_FILES or path.startswith(
        SCHEMA_SCOPE_PREFIXES
    )


# writer method -> proto wire type name
_WIRE = {
    "uint": "varint",
    "int": "varint",
    "sint": "varint",
    "bool": "varint",
    "sfixed64": "fixed64",
    "fixed64": "fixed64",
    "double": "fixed64",
    "sfixed32": "fixed32",
    "bytes": "bytes",
    "string": "bytes",
    "message": "bytes",
}

# FieldReader accessors / iter_fields loops mark a tag as parsed
_READER_METHODS = {
    "get",
    "get_all",
    "uint",
    "int64",
    "sfixed64",
    "bytes",
    "string",
    "bool",
}

_ENCODER_NAME_RE = re.compile(
    r"^(to_proto|to_proto_bytes|\w+_to_proto|encode_\w+|_enc_\w+"
    r"|hash_bytes|canonical_\w+|\w*_sign_bytes)$"
)
_DECODER_NAME_RE = re.compile(
    r"^(from_proto|from_proto_bytes|\w+_from_proto|decode_\w+|_dec_\w+)$"
)

_ANNOT_RE = re.compile(
    r"#\s*tmcheck:\s*(unparsed|unwritten)=([0-9, ]+)"
)


# ---------------------------------------------------------------------------
# extraction


class FieldWrite:
    __slots__ = ("tag", "method", "lineno", "repeated", "conditional", "node")

    def __init__(self, tag, method, lineno, repeated, conditional, node):
        self.tag = tag
        self.method = method
        self.lineno = lineno
        self.repeated = repeated
        self.conditional = conditional
        self.node = node

    def as_json(self) -> dict:
        return {
            "tag": self.tag,
            "method": self.method,
            "wire": _WIRE[self.method],
            "repeated": self.repeated,
            "conditional": self.conditional,
        }


class MessageSchema:
    """One extracted message: encoder field list + decoder tag set."""

    def __init__(self, name: str, path: str) -> None:
        self.name = name  # "types/vote.py::Vote"
        self.path = path
        self.enc_func: Optional[str] = None
        self.enc_lineno: int = 0
        self.dec_func: Optional[str] = None
        self.dec_lineno: int = 0
        self.fields: List[FieldWrite] = []
        self.parsed: Set[int] = set()
        self.unparsed_ok: Set[int] = set()
        self.unwritten_ok: Set[int] = set()
        self.reference: str = ""

    def as_json(self) -> dict:
        out = {
            "fields": [f.as_json() for f in self.fields],
            "parsed": sorted(self.parsed) if self.dec_func else None,
        }
        if self.reference:
            out["reference"] = self.reference
        if self.unparsed_ok:
            out["unparsed_ok"] = sorted(self.unparsed_ok)
        if self.unwritten_ok:
            out["unwritten_ok"] = sorted(self.unwritten_ok)
        return out


def _parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    out: Dict[ast.AST, ast.AST] = {}
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            out[child] = parent
    return out


def _docstring_reference(node: ast.AST) -> str:
    """First `reference:`-citing line of a docstring — the provenance
    link to the reference .proto recorded in schema.json. Callers fall
    back def -> class -> module, so a module-level citation (the
    dominant style in types/ and abci/codec.py) covers every message
    in the file unless a closer one exists."""
    doc = ast.get_docstring(node) or ""
    for line in doc.splitlines():
        if "reference:" in line.lower() or ".pb.go" in line or ".proto" in line:
            return line.strip()
    return ""


def _annotations(
    lines: Sequence[str], lo: int, hi: int
) -> Tuple[Set[int], Set[int]]:
    unparsed: Set[int] = set()
    unwritten: Set[int] = set()
    for i in range(max(lo - 1, 0), min(hi, len(lines))):
        m = _ANNOT_RE.search(lines[i])
        if not m:
            continue
        tags = {
            int(t) for t in m.group(2).replace(" ", "").split(",") if t
        }
        (unparsed if m.group(1) == "unparsed" else unwritten).update(tags)
    return unparsed, unwritten


def _func_end(node: ast.AST) -> int:
    return getattr(node, "end_lineno", node.lineno) or node.lineno


def _writer_vars(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and dotted_name(node.value.func).split(".")[-1] == "ProtoWriter"
        ):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def _primary_writer(fn: ast.AST, writers: Set[str]) -> Optional[str]:
    """The writer whose .finish() the function returns (possibly inside
    a wrapping call like length_prefixed(w.finish()))."""
    if len(writers) == 1:
        return next(iter(writers))
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            for sub in ast.walk(node.value):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "finish"
                    and isinstance(sub.func.value, ast.Name)
                    and sub.func.value.id in writers
                ):
                    return sub.func.value.id
    return None


def _collect_writes(
    fn: ast.AST, writer: str, parents: Dict[ast.AST, ast.AST]
) -> List[FieldWrite]:
    writes: List[FieldWrite] = []
    for node in ast.walk(fn):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == writer
            and node.func.attr in _WIRE
        ):
            continue
        if not node.args:
            continue
        tags: List[int] = []
        oneof = False
        arg0 = node.args[0]
        if isinstance(arg0, ast.Constant) and isinstance(arg0.value, int):
            tags = [arg0.value]
        elif isinstance(arg0, ast.Name):
            # the oneof idiom: `fieldno = {...: 1, ...: 2}[key]` — the
            # write emits exactly one of the dict's value tags
            tags = sorted(_dict_subscript_values(fn, arg0.id))
            oneof = bool(tags)
        if not tags:
            continue
        repeated = False
        conditional = False
        cur = parents.get(node)
        while cur is not None and cur is not fn:
            if isinstance(
                cur,
                (ast.For, ast.AsyncFor, ast.While, ast.comprehension),
            ):
                repeated = True
            if isinstance(cur, ast.If):
                conditional = True
            cur = parents.get(cur)
        for tag in tags:
            writes.append(
                FieldWrite(
                    tag,
                    node.func.attr,
                    node.lineno,
                    repeated,
                    conditional or oneof,
                    node,
                )
            )
    writes.sort(key=lambda w: (w.lineno, w.tag))
    return writes


def _dict_subscript_values(fn: ast.AST, name: str) -> Set[int]:
    """Int values of `name = {<...>: 1, <...>: 2}[<expr>]` assignments
    in `fn` — the computed-tag oneof idiom."""
    out: Set[int] = set()
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == name for t in node.targets
        )):
            continue
        val = node.value
        if isinstance(val, ast.Subscript) and isinstance(
            val.value, ast.Dict
        ):
            for v in val.value.values:
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    out.add(v.value)
    return out


def _branch_path(
    node: ast.AST, fn: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> List[Tuple[int, str]]:
    """The chain of (if-node-id, arm) pairs enclosing `node` — two
    writes whose paths diverge at a common If are mutually exclusive."""
    path: List[Tuple[int, str]] = []
    cur = node
    while cur is not None and cur is not fn:
        parent = parents.get(cur)
        if isinstance(parent, ast.If):
            arm = "body" if cur in parent.body else "orelse"
            path.append((id(parent), arm))
        cur = parent
    path.reverse()
    return path


def _mutually_exclusive(
    a: FieldWrite, b: FieldWrite, fn: ast.AST, parents
) -> bool:
    pa = _branch_path(a.node, fn, parents)
    pb = _branch_path(b.node, fn, parents)
    for (ia, arma), (ib, armb) in zip(pa, pb):
        if ia == ib and arma != armb:
            return True
        if ia != ib:
            break
    return False


def _is_iter_fields_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and dotted_name(node.func).split(".")[-1] == "iter_fields"
    )


def _collect_reads(fn: ast.AST) -> Set[int]:
    """Tags a decoder consumes: FieldReader accessor calls with literal
    tags (through a reader variable or chained directly off
    `FieldReader(data)`), and `if f == N` / `elif f in <literal
    container>` comparisons on an iter_fields loop variable (For loops
    and comprehensions). Readers created INSIDE an iter_fields loop
    parse a nested submessage and do not count toward this message."""
    reads: Set[int] = set()
    # nodes living inside an iter_fields For body (nested submessage
    # parsing region)
    nested: Set[int] = set()
    loop_vars: Set[str] = set()
    for node in ast.walk(fn):
        it = None
        tgt = None
        if isinstance(node, (ast.For, ast.AsyncFor)):
            it, tgt = node.iter, node.target
            if _is_iter_fields_call(it):
                for sub in ast.walk(node):
                    nested.add(id(sub))
        elif isinstance(
            node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
        ):
            for gen in node.generators:
                if _is_iter_fields_call(gen.iter):
                    t = gen.target
                    if isinstance(t, ast.Tuple) and t.elts:
                        t = t.elts[0]
                    if isinstance(t, ast.Name):
                        loop_vars.add(t.id)
        if it is not None and _is_iter_fields_call(it):
            if isinstance(tgt, ast.Tuple) and tgt.elts:
                first = tgt.elts[0]
                if isinstance(first, ast.Name):
                    loop_vars.add(first.id)
    # reader vars: r = FieldReader(...) — outside nested regions only
    readers: Set[str] = set()
    local_containers: Dict[str, Set[int]] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            cname = dotted_name(node.value.func).split(".")[-1]
            if cname == "FieldReader" and id(node) not in nested:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        readers.add(tgt.id)
        if isinstance(node, ast.Assign) and isinstance(
            node.value, (ast.Dict, ast.Set, ast.Tuple, ast.List)
        ):
            keys: Set[int] = set()
            elems = (
                node.value.keys
                if isinstance(node.value, ast.Dict)
                else node.value.elts
            )
            for e in elems:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    keys.add(e.value)
            if keys:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        local_containers[tgt.id] = keys
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _READER_METHODS
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, int)
        ):
            recv = node.func.value
            via_var = (
                isinstance(recv, ast.Name) and recv.id in readers
            )
            chained = (
                isinstance(recv, ast.Call)
                and dotted_name(recv.func).split(".")[-1] == "FieldReader"
                and id(node) not in nested
            )
            if via_var or chained:
                reads.add(node.args[0].value)
        elif isinstance(node, ast.Compare) and len(node.ops) == 1:
            left = node.left
            if not (isinstance(left, ast.Name) and left.id in loop_vars):
                continue
            comp = node.comparators[0]
            if isinstance(node.ops[0], ast.Eq):
                if isinstance(comp, ast.Constant) and isinstance(
                    comp.value, int
                ):
                    reads.add(comp.value)
            elif isinstance(node.ops[0], ast.In):
                if isinstance(comp, ast.Name) and comp.id in local_containers:
                    reads.update(local_containers[comp.id])
                elif isinstance(comp, (ast.Tuple, ast.Set, ast.List)):
                    for e in comp.elts:
                        if isinstance(e, ast.Constant) and isinstance(
                            e.value, int
                        ):
                            reads.add(e.value)
    return reads


def _pair_key(path: str, class_name: Optional[str], fname: str) -> str:
    """Message identity an encoder/decoder pair shares."""
    if class_name:
        return f"{path}::{class_name}"
    m = re.match(r"^(?:_enc_|encode_)(\w+)$", fname)
    if m:
        return f"{path}::{m.group(1)}"
    m = re.match(r"^(?:_dec_|decode_)(\w+)$", fname)
    if m:
        return f"{path}::{m.group(1)}"
    m = re.match(r"^(\w+)_(?:to|from)_proto$", fname)
    if m:
        return f"{path}::{m.group(1)}"
    return f"{path}::{fname}"


def extract_module(
    source: str, path: str, tree: Optional[ast.AST] = None
) -> Tuple[Dict[str, MessageSchema], List[Violation]]:
    """Extract every message schema from one module; also returns
    schema-order violations found during extraction. `tree` reuses an
    already-parsed AST (the shared lint.py substrate) — extraction
    only reads it."""
    if tree is None:
        tree = ast.parse(source, filename=path)
    lines = source.splitlines()
    parents = _parents(tree)
    module_ref = _docstring_reference(tree)
    messages: Dict[str, MessageSchema] = {}
    order_violations: List[Violation] = []

    def visit(body, class_name, class_node):
        for node in body:
            if isinstance(node, ast.ClassDef):
                visit(node.body, node.name, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                handle(node, class_name, class_node)

    def handle(fn, class_name, class_node):
        is_enc = bool(_ENCODER_NAME_RE.match(fn.name))
        is_dec = bool(_DECODER_NAME_RE.match(fn.name))
        if not (is_enc or is_dec):
            return
        # only the canonical method pair (and encode_X/_enc_X name
        # pairs) share a message; other encoders (hash_bytes, the
        # canonical sign-bytes builders) are distinct encode-only
        # messages — a hash-leaf schema is not the wire schema
        pairable = fn.name in (
            "to_proto",
            "to_proto_bytes",
            "from_proto",
            "from_proto_bytes",
        ) or not class_name
        key = _pair_key(path, class_name, fn.name)
        if not pairable:
            key = f"{key}.{fn.name}"
        if is_enc:
            writers = _writer_vars(fn)
            if not writers:
                return
            primary = _primary_writer(fn, writers)
            if primary is None:
                return
            msg = messages.setdefault(key, MessageSchema(key, path))
            msg.enc_func = fn.name
            msg.enc_lineno = fn.lineno
            msg.fields = _collect_writes(fn, primary, parents)
            ref = (
                _docstring_reference(fn)
                or (_docstring_reference(class_node) if class_node else "")
                or module_ref
            )
            if ref and not msg.reference:
                msg.reference = ref
            up, uw = _annotations(lines, fn.lineno, _func_end(fn))
            msg.unparsed_ok |= up
            msg.unwritten_ok |= uw
            # ascending-tag check on each control-flow path
            flat = msg.fields
            for i in range(len(flat)):
                for j in range(i + 1, len(flat)):
                    a, b = flat[i], flat[j]
                    if a.tag <= b.tag:
                        continue
                    if _mutually_exclusive(a, b, fn, parents):
                        continue
                    order_violations.append(
                        Violation(
                            rule="schema-order",
                            path=path,
                            line=b.lineno,
                            col=0,
                            message=(
                                f"{key}: field {b.tag} written after field "
                                f"{a.tag} (line {a.lineno}) — non-canonical "
                                "emission order; ProtoWriter will raise at "
                                "runtime"
                            ),
                            source=(
                                lines[b.lineno - 1].strip()
                                if b.lineno <= len(lines)
                                else ""
                            ),
                        )
                    )
                    break
        if is_dec:
            msg = messages.setdefault(key, MessageSchema(key, path))
            msg.dec_func = fn.name
            msg.dec_lineno = fn.lineno
            msg.parsed |= _collect_reads(fn)
            if not msg.reference:
                msg.reference = (
                    _docstring_reference(fn)
                    or (
                        _docstring_reference(class_node)
                        if class_node
                        else ""
                    )
                    or module_ref
                )
            up, uw = _annotations(lines, fn.lineno, _func_end(fn))
            msg.unparsed_ok |= up
            msg.unwritten_ok |= uw

    visit(tree.body, None, None)
    # prune entries with nothing statically extractable: decoder-only
    # passthroughs, and registry-driven codecs whose tags are runtime
    # values on both sides (pubkey_to_proto/_from_proto — the ABCI
    # _enc_pub_key twin with literal tags covers that oneof's schema)
    for key in [
        k
        for k, m in messages.items()
        if not m.fields and not m.parsed
    ]:
        del messages[key]
    return messages, order_violations


def extract_package(
    root: Optional[str] = None, pkg=None
) -> Tuple[Dict[str, MessageSchema], List[Violation]]:
    """`pkg`: an already-built tmcheck callgraph Package — its modules
    carry the parsed trees, so a full-gate run parses the package
    exactly once across all sections."""
    root = root or (pkg.root if pkg is not None else package_root())
    messages: Dict[str, MessageSchema] = {}
    violations: List[Violation] = []
    if pkg is not None:
        for rel in sorted(pkg.modules):
            if not in_schema_scope(rel):
                continue
            mod = pkg.modules[rel]
            msgs, ov = extract_module(mod.source, rel, tree=mod.tree)
            messages.update(msgs)
            violations.extend(ov)
        return messages, violations
    for abspath in iter_py_files(root):
        rel = os.path.relpath(abspath, root).replace(os.sep, "/")
        if not in_schema_scope(rel):
            continue
        try:
            with open(abspath, "r", encoding="utf-8") as f:
                source = f.read()
            msgs, ov = extract_module(source, rel)
        except (SyntaxError, OSError):
            continue
        messages.update(msgs)
        violations.extend(ov)
    return messages, violations


# ---------------------------------------------------------------------------
# symmetry


def symmetry_violations(
    messages: Dict[str, MessageSchema]
) -> List[Violation]:
    out: List[Violation] = []
    for key in sorted(messages):
        msg = messages[key]
        if msg.enc_func is None or msg.dec_func is None:
            continue
        written = {f.tag for f in msg.fields}
        for tag in sorted(written - msg.parsed - msg.unparsed_ok):
            out.append(
                Violation(
                    rule="schema-symmetry",
                    path=msg.path,
                    line=msg.enc_lineno,
                    col=0,
                    message=(
                        f"{key}: field {tag} is written by {msg.enc_func} "
                        f"but never parsed by {msg.dec_func}; annotate "
                        "`# tmcheck: unparsed={t} — why` if deliberate"
                    ).replace("{t}", str(tag)),
                    source=f"{key} field {tag} unparsed",
                )
            )
        for tag in sorted(msg.parsed - written - msg.unwritten_ok):
            out.append(
                Violation(
                    rule="schema-symmetry",
                    path=msg.path,
                    line=msg.dec_lineno,
                    col=0,
                    message=(
                        f"{key}: field {tag} is parsed by {msg.dec_func} "
                        f"but never written by {msg.enc_func}; annotate "
                        "`# tmcheck: unwritten={t} — why` if deliberate"
                    ).replace("{t}", str(tag)),
                    source=f"{key} field {tag} unwritten",
                )
            )
    return out


# ---------------------------------------------------------------------------
# golden table


def load_golden(path: Optional[str] = None) -> Optional[dict]:
    path = path or GOLDEN_PATH
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def save_golden(
    messages: Dict[str, MessageSchema], path: Optional[str] = None
) -> dict:
    path = path or GOLDEN_PATH
    data = {
        "version": 1,
        "generated_by": "scripts/lint.py --schema-update",
        "note": (
            "Golden wire schema for every hand-codec message: field "
            "tags, wire types, writer methods, emission order, "
            "repeated/conditional flags, and the decoder's parsed-tag "
            "set. Each entry's `reference` records the reference "
            ".proto/.pb.go message it mirrors (from the codec's "
            "docstring citation). ANY diff against this table is a "
            "tier-1 failure; after a reviewed protocol change, "
            "regenerate with scripts/lint.py --schema-update and "
            "review the diff like a .proto change."
        ),
        "messages": {k: messages[k].as_json() for k in sorted(messages)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=False)
        f.write("\n")
    return data


def diff_golden(
    messages: Dict[str, MessageSchema], golden: dict
) -> List[Violation]:
    out: List[Violation] = []
    gmsgs = golden.get("messages", {})
    for key in sorted(set(gmsgs) - set(messages)):
        out.append(
            Violation(
                rule="schema-drift",
                path=key.split("::")[0],
                line=1,
                col=0,
                message=(
                    f"{key}: message present in golden schema.json but no "
                    "longer extracted — codec deleted or renamed; if "
                    "intended, run scripts/lint.py --schema-update"
                ),
                source=f"missing message {key}",
            )
        )
    for key in sorted(messages):
        msg = messages[key]
        gold = gmsgs.get(key)
        if gold is None:
            out.append(
                Violation(
                    rule="schema-drift",
                    path=msg.path,
                    line=msg.enc_lineno or msg.dec_lineno or 1,
                    col=0,
                    message=(
                        f"{key}: new codec message not in the golden "
                        "schema.json — add it via scripts/lint.py "
                        "--schema-update (and cite the reference .proto "
                        "in the docstring)"
                    ),
                    source=f"new message {key}",
                )
            )
            continue
        cur = msg.as_json()
        for field_name in ("fields", "parsed"):
            if cur.get(field_name) != gold.get(field_name):
                out.append(
                    Violation(
                        rule="schema-drift",
                        path=msg.path,
                        line=msg.enc_lineno or msg.dec_lineno or 1,
                        col=0,
                        message=(
                            f"{key}: {field_name} drifted from golden "
                            f"schema.json\n    golden:    "
                            f"{json.dumps(gold.get(field_name))}\n"
                            f"    extracted: "
                            f"{json.dumps(cur.get(field_name))}"
                        ),
                        source=f"{key} {field_name} drift",
                    )
                )
    return out


def schema_violations(
    root: Optional[str] = None,
    golden_path: Optional[str] = None,
    pkg=None,
) -> List[Violation]:
    """The full schema gate: extraction (order check) + symmetry +
    golden diff. `pkg` reuses the shared parsed-module substrate."""
    messages, violations = extract_package(root, pkg=pkg)
    violations.extend(symmetry_violations(messages))
    golden = load_golden(golden_path)
    if golden is None:
        violations.append(
            Violation(
                rule="schema-drift",
                path="analysis/tmcheck/schema.json",
                line=1,
                col=0,
                message=(
                    "golden schema.json missing — generate it with "
                    "scripts/lint.py --schema-update"
                ),
                source="missing schema.json",
            )
        )
    else:
        violations.extend(diff_golden(messages, golden))
    violations.sort(key=lambda v: (v.path, v.line, v.rule, v.message))
    return violations


def check_package_schema(root: Optional[str] = None) -> List[Violation]:
    return schema_violations(root)
