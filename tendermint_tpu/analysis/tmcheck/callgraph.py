"""Package-wide call-graph construction for tmcheck.

tmlint proves per-module, per-line facts; the two deepest invariants
need whole-program reach: "no nondeterminism source can reach a
sign-bytes/hash sink through ANY call path" is a property of the call
graph, not of one file. This module builds that graph with stdlib
`ast` only — every function/method in the package becomes a node, and
call sites are resolved through the real import structure (absolute
and relative imports, `import x as y` aliases, from-imports via the
same machinery tmlint's `Module.from_import_orig` uses per-module),
plus the small amount of local type inference the codebase's idiom
makes reliable:

- `f(...)` — module-level function or from-imported function/class
- `self.m(...)` / `cls.m(...)` — methods of the enclosing class (and
  same-module / imported base classes)
- `mod.f(...)` — attribute call through an imported module
- `x.m(...)` where `x = SomeClass(...)` locally — the ProtoWriter /
  FieldReader idiom
- `self.attr.m(...)` where `attr` is annotated on the class (dataclass
  fields, `self.x: T = ...` in __init__)
- `v.m(...)` where `v` iterates a List[T]/Sequence[T]-annotated
  attribute — the `for v in self.validators: v.hash_bytes()` idiom
- `g.m(...)` where `g` is a module-level `g = SomeClass(...)` or
  `g = factory(...)` whose factory has a `-> SomeClass` return
  annotation — the `_m_state = M.new_gauge(...); _m_state.set(...)`
  metric-instrument idiom (tmrace needs these edges to see the
  lock acquisitions inside metric methods)
- `v.m(...)` where `v = G.pop(...)` / `G.get(...)` / `G[...]` and `G`
  is a module-level global annotated `Dict[K, V]` — the registry
  idiom (`old = _REGISTRY.pop(name); old._cancel_timer_locked()`)

Unresolvable calls (dynamic hooks, higher-order functions) produce no
edge: the analysis is deliberately under-approximate on edges and
over-approximate on sources, and the docs say so. Calls that resolve
to nothing inside the package are returned as *external* dotted names
("time.time", "os.urandom") for the taint pass to classify.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from ..tmlint import dotted_name as _dotted
from ..tmlint import iter_py_files

__all__ = ["CallSite", "FuncInfo", "ModuleIndex", "Package", "build_package"]


_CONTAINER_GENERICS = {
    "List",
    "Sequence",
    "Tuple",
    "Optional",
    "Iterable",
    "Set",
    "FrozenSet",
    "list",
    "tuple",
    "set",
}


def _annotation_type_name(node: Optional[ast.AST]) -> str:
    """The bare class name of an annotation, unwrapping one layer of
    Optional[T] / List[T] / "T" string forms. Returns "" when the
    annotation isn't a simple type."""
    if node is None:
        return ""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        # string annotation: 'BlockID' or "Optional[Validator]"
        try:
            return _annotation_type_name(
                ast.parse(node.value, mode="eval").body
            )
        except SyntaxError:
            return ""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Subscript):
        base = _annotation_type_name(node.value)
        if base in _CONTAINER_GENERICS:
            inner = node.slice
            if isinstance(inner, ast.Tuple) and inner.elts:
                inner = inner.elts[0]
            return _annotation_type_name(inner)
        return base
    return ""


def _element_type_name(node: Optional[ast.AST]) -> str:
    """Element type of a container annotation (List[T] -> T); "" when
    not a container."""
    if node is None:
        return ""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            return _element_type_name(ast.parse(node.value, mode="eval").body)
        except SyntaxError:
            return ""
    if isinstance(node, ast.Subscript):
        base = ""
        if isinstance(node.value, ast.Name):
            base = node.value.id
        elif isinstance(node.value, ast.Attribute):
            base = node.value.attr
        if base in _CONTAINER_GENERICS and base not in (
            "Optional",
        ):
            inner = node.slice
            if isinstance(inner, ast.Tuple) and inner.elts:
                inner = inner.elts[0]
            return _annotation_type_name(inner)
        if base == "Optional":
            return _element_type_name(
                node.slice.elts[0]
                if isinstance(node.slice, ast.Tuple) and node.slice.elts
                else node.slice
            )
    return ""


def _value_type_name(node: Optional[ast.AST]) -> str:
    """Value type of a mapping annotation (Dict[K, V] -> V); "" when
    not a mapping."""
    if node is None:
        return ""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            return _value_type_name(ast.parse(node.value, mode="eval").body)
        except SyntaxError:
            return ""
    if isinstance(node, ast.Subscript):
        base = ""
        if isinstance(node.value, ast.Name):
            base = node.value.id
        elif isinstance(node.value, ast.Attribute):
            base = node.value.attr
        if base in ("Dict", "dict", "Mapping", "MutableMapping"):
            inner = node.slice
            if isinstance(inner, ast.Tuple) and len(inner.elts) == 2:
                return _annotation_type_name(inner.elts[1])
        if base == "Optional":
            return _value_type_name(
                node.slice.elts[0]
                if isinstance(node.slice, ast.Tuple) and node.slice.elts
                else node.slice
            )
    return ""


class CallSite:
    """One call expression inside a function body.

    Exactly one of `target` (an in-package FuncInfo key) or `external`
    (a resolved dotted name like "time.time") is set; both are None
    for calls the resolver cannot identify."""

    __slots__ = ("target", "external", "lineno", "col")

    def __init__(
        self,
        target: Optional[Tuple[str, str]],
        external: Optional[str],
        lineno: int,
        col: int,
    ) -> None:
        self.target = target
        self.external = external
        self.lineno = lineno
        self.col = col


class FuncInfo:
    """One function or method: (path, qualname) identity, its AST node,
    and the resolved calls in its body (nested defs excluded — they
    are their own nodes)."""

    __slots__ = (
        "path",
        "qualname",
        "node",
        "lineno",
        "class_name",
        "calls",
    )

    def __init__(self, path, qualname, node, class_name):
        self.path = path
        self.qualname = qualname
        self.node = node
        self.lineno = node.lineno
        self.class_name = class_name
        self.calls: List[CallSite] = []

    @property
    def key(self) -> Tuple[str, str]:
        return (self.path, self.qualname)

    def render(self) -> str:
        return f"{self.path}:{self.qualname}"


class ModuleIndex:
    """Per-module name tables: defs, classes (methods, base names,
    attribute annotations), and the import environment resolved to
    package-relative paths."""

    def __init__(self, path: str, source: str, pkg_name: str) -> None:
        self.path = path  # posix path relative to the package root
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.pkg_name = pkg_name
        # dotted module of this file inside the package, e.g.
        # "types.vote" for types/vote.py, "types" for types/__init__.py,
        # "" for the package root __init__.py (so `from <pkg> import X`
        # / `from . import X` re-exports through the root resolve)
        mod = path[:-3].replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        elif mod == "__init__":
            mod = ""
        self.dotted = mod
        self.functions: Dict[str, ast.AST] = {}  # module-level defs
        self.classes: Dict[str, dict] = {}  # name -> class record
        self.import_alias: Dict[str, str] = {}  # local -> dotted module
        # local -> (internal module path | None, external dotted | None,
        #           original name)
        self.from_imports: Dict[str, Tuple[Optional[str], Optional[str], str]] = {}
        # module-level `x = SomeCall(...)` assignments, resolved to
        # their concrete class by Package._infer_module_vars (the
        # resolver needs cross-module return annotations):
        # name -> (owner ModuleIndex, class name)
        self.var_class: Dict[str, Tuple["ModuleIndex", str]] = {}
        # module-level globals annotated Dict[K, V]: name -> V (the
        # value class name, resolvable in THIS module's namespace)
        self.var_value_types: Dict[str, str] = {}
        # raw module-level `x = <Call>` sites awaiting inference
        self._var_assigns: List[Tuple[str, ast.Call]] = []
        self._index()

    # -- import resolution --

    def _resolve_relative(self, module: Optional[str], level: int) -> str:
        """Absolute dotted target of a (possibly relative) from-import,
        WITHOUT the package prefix when internal; e.g. in types/vote.py,
        `from ..encoding.proto import X` -> "encoding.proto"."""
        if level == 0:
            mod = module or ""
            prefix = self.pkg_name + "."
            if mod == self.pkg_name:
                return ""
            if mod.startswith(prefix):
                return mod[len(prefix):]
            return "!" + mod  # external, tagged
        # relative: climb from this module's package
        parts = self.dotted.split(".")[:-1] if "." in self.dotted else []
        if self.path.endswith("__init__.py"):
            parts = self.dotted.split(".") if self.dotted else []
        drop = level - 1
        if drop > len(parts):
            return "!" + (module or "")
        base = parts[: len(parts) - drop]
        if module:
            base = base + module.split(".")
        return ".".join(base)

    def _index(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    self.import_alias[local] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom):
                target = self._resolve_relative(node.module, node.level)
                for a in node.names:
                    local = a.asname or a.name
                    if target.startswith("!"):
                        self.from_imports[local] = (None, target[1:], a.name)
                    else:
                        self.from_imports[local] = (target, None, a.name)
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self._var_assigns.append((tgt.id, node.value))
            elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name
            ):
                vt = _value_type_name(node.annotation)
                if vt:
                    self.var_value_types[node.target.id] = vt
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                methods: Dict[str, ast.AST] = {}
                attrs: Dict[str, str] = {}
                for item in node.body:
                    if isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        methods[item.name] = item
                    elif isinstance(item, ast.AnnAssign) and isinstance(
                        item.target, ast.Name
                    ):
                        t = _annotation_type_name(item.annotation)
                        if t:
                            attrs[item.target.id] = t
                        et = _element_type_name(item.annotation)
                        if et:
                            attrs["*" + item.target.id] = et
                        vt = _value_type_name(item.annotation)
                        if vt:
                            attrs["@" + item.target.id] = vt
                # `self.x: T = ...` annotations inside methods
                for item in node.body:
                    if not isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        continue
                    for sub in ast.walk(item):
                        if (
                            isinstance(sub, ast.AnnAssign)
                            and isinstance(sub.target, ast.Attribute)
                            and isinstance(sub.target.value, ast.Name)
                            and sub.target.value.id == "self"
                        ):
                            t = _annotation_type_name(sub.annotation)
                            if t:
                                attrs.setdefault(sub.target.attr, t)
                            et = _element_type_name(sub.annotation)
                            if et:
                                attrs.setdefault("*" + sub.target.attr, et)
                            vt = _value_type_name(sub.annotation)
                            if vt:
                                attrs.setdefault("@" + sub.target.attr, vt)
                self.classes[node.name] = {
                    "node": node,
                    "methods": methods,
                    "bases": [_dotted(b) for b in node.bases],
                    "attrs": attrs,
                }


class Package:
    """The whole-package call graph."""

    def __init__(self, root: str, pkg_name: str) -> None:
        self.root = root
        self.pkg_name = pkg_name
        self.modules: Dict[str, ModuleIndex] = {}
        self.functions: Dict[Tuple[str, str], FuncInfo] = {}
        # dotted module -> path for internal modules
        self._by_dotted: Dict[str, str] = {}
        # class name -> paths defining it (find_class falls back to a
        # UNIQUELY-named class for unimported references: factory
        # return annotations name classes their caller never imports)
        self._class_homes: Dict[str, List[str]] = {}

    # -- lookups --

    def module_for_dotted(self, dotted: str) -> Optional[ModuleIndex]:
        path = self._by_dotted.get(dotted)
        return self.modules.get(path) if path else None

    def find_class(
        self, mod: ModuleIndex, name: str
    ) -> Optional[Tuple[ModuleIndex, dict]]:
        """Resolve a class name visible in `mod` (local or imported)."""
        rec = mod.classes.get(name)
        if rec is not None:
            return mod, rec
        fi = mod.from_imports.get(name)
        if fi is not None and fi[0] is not None:
            target = self.module_for_dotted(fi[0])
            if target is not None:
                rec = target.classes.get(fi[2])
                if rec is not None:
                    return target, rec
                # re-exported through an __init__: chase one more hop
                fi2 = target.from_imports.get(fi[2])
                if fi2 is not None and fi2[0] is not None:
                    t2 = self.module_for_dotted(fi2[0])
                    if t2 is not None and fi2[2] in t2.classes:
                        return t2, t2.classes[fi2[2]]
        # a name `mod` neither defines nor imports, defined by exactly
        # ONE module in the package: a factory's `-> CircuitBreaker`
        # seen from a caller that only imports the factory's module
        if name not in mod.from_imports and name not in mod.import_alias:
            homes = self._class_homes.get(name)
            if homes is not None and len(homes) == 1:
                owner = self.modules[homes[0]]
                return owner, owner.classes[name]
        return None

    def _method_key(
        self, mod: ModuleIndex, class_name: str, method: str, _depth: int = 0
    ) -> Optional[Tuple[str, str]]:
        """(path, qualname) of class_name.method, following same/
        cross-module base classes a few levels deep."""
        if _depth > 4:
            return None
        found = self.find_class(mod, class_name)
        if found is None:
            return None
        owner, rec = found
        if method in rec["methods"]:
            return (owner.path, f"{_class_name(rec)}.{method}")
        for base in rec["bases"]:
            base = base.split(".")[-1]
            key = self._method_key(owner, base, method, _depth + 1)
            if key is not None:
                return key
        return None

    # -- construction --

    def build(self) -> None:
        for abspath in iter_py_files(self.root):
            rel = os.path.relpath(abspath, self.root).replace(os.sep, "/")
            try:
                with open(abspath, "r", encoding="utf-8") as f:
                    source = f.read()
                mod = ModuleIndex(rel, source, self.pkg_name)
            except (SyntaxError, OSError):
                continue
            self.modules[rel] = mod
            self._by_dotted[mod.dotted] = rel
        for mod in self.modules.values():
            for cname in mod.classes:
                self._class_homes.setdefault(cname, []).append(mod.path)
        for mod in self.modules.values():
            self._collect_functions(mod)
        for mod in self.modules.values():
            self._infer_module_vars(mod)
        for mod in self.modules.values():
            self._resolve_module_calls(mod)

    def _returned_class(
        self, owner: ModuleIndex, fn_node: ast.AST
    ) -> Optional[Tuple[ModuleIndex, str]]:
        """The concrete class a function's `-> T` annotation names,
        resolved in the DEFINING module's namespace."""
        tname = _annotation_type_name(getattr(fn_node, "returns", None))
        if not tname:
            return None
        found = self.find_class(owner, tname)
        if found is None:
            return None
        fmod, rec = found
        return (fmod, rec["node"].name)

    def _call_result_class(
        self, mod: ModuleIndex, call: ast.Call
    ) -> Optional[Tuple[ModuleIndex, str]]:
        """The concrete class an `<expr>(...)` call produces: a direct
        constructor, or a factory through its `-> T` return annotation
        (`M.new_gauge(...)`, `breaker.fresh(...)`)."""
        func = call.func
        resolved: Optional[Tuple[ModuleIndex, str]] = None
        if isinstance(func, ast.Name):
            n = func.id
            found = self.find_class(mod, n)
            if found is not None:
                resolved = (found[0], found[1]["node"].name)
            elif n in mod.functions:
                resolved = self._returned_class(mod, mod.functions[n])
            else:
                fi_entry = mod.from_imports.get(n)
                if fi_entry is not None and fi_entry[0] is not None:
                    target = self.module_for_dotted(fi_entry[0])
                    if (
                        target is not None
                        and fi_entry[2] in target.functions
                    ):
                        resolved = self._returned_class(
                            target, target.functions[fi_entry[2]]
                        )
        elif isinstance(func, ast.Attribute) and isinstance(
            func.value, ast.Name
        ):
            target = None
            alias = mod.import_alias.get(func.value.id)
            if alias is not None:
                prefix = self.pkg_name + "."
                if alias.startswith(prefix):
                    target = self.module_for_dotted(alias[len(prefix):])
                elif alias == self.pkg_name:
                    target = self.module_for_dotted("")
            else:
                fi_entry = mod.from_imports.get(func.value.id)
                if fi_entry is not None and fi_entry[0] is not None:
                    base = (
                        fi_entry[0] + "." + fi_entry[2]
                        if fi_entry[0]
                        else fi_entry[2]
                    )
                    target = self.module_for_dotted(base)
            if target is not None:
                if func.attr in target.classes:
                    resolved = (target, func.attr)
                elif func.attr in target.functions:
                    resolved = self._returned_class(
                        target, target.functions[func.attr]
                    )
        return resolved

    def _infer_module_vars(self, mod: ModuleIndex) -> None:
        """Resolve module-level `x = <Call>(...)` globals to concrete
        classes: direct constructors, and factory calls through a
        `-> T` return annotation (`_m_state = M.new_gauge(...)`)."""
        for name, call in mod._var_assigns:
            resolved = self._call_result_class(mod, call)
            if resolved is not None:
                mod.var_class[name] = resolved

    def _collect_functions(self, mod: ModuleIndex) -> None:
        # defs are collected at ANY statement depth — a worker spawned
        # from inside an `if`/`with`/`try` block (the cmd stdin-reader
        # idiom) is still a graph node; only defs nested in OTHER defs
        # get the dotted qualname prefix
        def visit(node, prefix, class_name):
            for item in ast.iter_child_nodes(node):
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{prefix}{item.name}"
                    fi = FuncInfo(mod.path, qual, item, class_name)
                    self.functions[fi.key] = fi
                    visit(item, qual + ".", class_name)
                elif isinstance(item, ast.ClassDef):
                    visit(item, f"{prefix}{item.name}.", item.name)
                elif not isinstance(item, ast.Lambda):
                    visit(item, prefix, class_name)

        visit(mod.tree, "", None)

    # -- call resolution --

    def _local_types(self, mod: ModuleIndex, fn: ast.AST) -> Dict[str, str]:
        """varname -> class name for `x = SomeClass(...)` assignments
        (and `for v in self.<attr>` / comprehensions over annotated
        container attributes)."""
        out: Dict[str, str] = {}
        class_attrs: Dict[str, str] = {}
        class_methods: Dict[str, ast.AST] = {}
        # class attr annotations visible through `self`
        for rec in mod.classes.values():
            for m in rec["methods"].values():
                if m is fn:
                    class_attrs = rec["attrs"]
                    class_methods = rec["methods"]
        for node in _body_walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                cname = _dotted(node.value.func).split(".")[-1]
                if cname and (
                    cname in mod.classes
                    or cname in mod.from_imports
                ):
                    if cname[:1].isupper():
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                out[tgt.id] = cname
                else:
                    # x = factory(...) / x = mod.factory(...) through
                    # the factory's `-> T` return annotation — the
                    # `b = breaker.fresh(name); b.set_probe(fn)` idiom
                    res = self._call_result_class(mod, node.value)
                    if res is not None:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                out[tgt.id] = res[1]
                    else:
                        # x = self.helper() through the enclosing
                        # class's own `-> T`-annotated method — the
                        # `mp = self._require_mempool()` guard idiom
                        f0 = node.value.func
                        if (
                            isinstance(f0, ast.Attribute)
                            and isinstance(f0.value, ast.Name)
                            and f0.value.id == "self"
                            and f0.attr in class_methods
                        ):
                            rc = self._returned_class(
                                mod, class_methods[f0.attr]
                            )
                            if rc is not None:
                                for tgt in node.targets:
                                    if isinstance(tgt, ast.Name):
                                        out[tgt.id] = rc[1]
                # y = G.pop(...) / G.get(...) where G is a module-level
                # Dict[K, V] global — the registry idiom
                f = node.value.func
                if (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.attr in ("pop", "get", "setdefault")
                    and f.value.id in mod.var_value_types
                ):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            out[tgt.id] = mod.var_value_types[f.value.id]
                # y = self.attr.get(...) on a Dict[K, V]-annotated
                # instance attribute — the per-object registry idiom
                # (`ps = self.peers.get(peer_id)` in every reactor)
                if (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Attribute)
                    and isinstance(f.value.value, ast.Name)
                    and f.value.value.id == "self"
                    and f.attr in ("pop", "get", "setdefault")
                    and "@" + f.value.attr in class_attrs
                ):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            out[tgt.id] = class_attrs["@" + f.value.attr]
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Subscript
            ):
                # y = G[...] on a Dict[K, V]-annotated global
                sub = node.value.value
                if (
                    isinstance(sub, ast.Name)
                    and sub.id in mod.var_value_types
                ):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            out[tgt.id] = mod.var_value_types[sub.id]
                # y = self.attr[...] on a Dict[K, V]-annotated attribute
                if (
                    isinstance(sub, ast.Attribute)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "self"
                    and "@" + sub.attr in class_attrs
                ):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            out[tgt.id] = class_attrs["@" + sub.attr]
            it = None
            tgt = None
            if isinstance(node, (ast.For, ast.AsyncFor)):
                it, tgt = node.iter, node.target
            elif isinstance(
                node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
            ):
                for gen in node.generators:
                    et = self._iter_elem_type(mod, class_attrs, gen.iter)
                    if et and isinstance(gen.target, ast.Name):
                        out[gen.target.id] = et
            if it is not None and isinstance(tgt, ast.Name):
                et = self._iter_elem_type(mod, class_attrs, it)
                if et:
                    out[tgt.id] = et
        return out

    def _iter_elem_type(
        self, mod: ModuleIndex, class_attrs: Dict[str, str], it: ast.AST
    ) -> str:
        if (
            isinstance(it, ast.Attribute)
            and isinstance(it.value, ast.Name)
            and it.value.id == "self"
        ):
            return class_attrs.get("*" + it.attr, "")
        return ""

    def _resolve_module_calls(self, mod: ModuleIndex) -> None:
        for fi in self.functions.values():
            if fi.path != mod.path:
                continue
            local_types = self._local_types(mod, fi.node)
            class_attrs: Dict[str, str] = {}
            if fi.class_name and fi.class_name in mod.classes:
                class_attrs = mod.classes[fi.class_name]["attrs"]
            for node in _body_walk(fi.node):
                if isinstance(node, ast.Call):
                    site = self._resolve_call(
                        mod, fi, node, local_types, class_attrs
                    )
                    if site is not None:
                        fi.calls.append(site)

    def _resolve_call(
        self,
        mod: ModuleIndex,
        fi: FuncInfo,
        node: ast.Call,
        local_types: Dict[str, str],
        class_attrs: Dict[str, str],
    ) -> Optional[CallSite]:
        func = node.func
        lineno = node.lineno
        col = node.col_offset

        def internal(key):
            if key is not None and key in self.functions:
                return CallSite(key, None, lineno, col)
            return None

        def external(name):
            return CallSite(None, name, lineno, col)

        # plain name call: local function, from-import, or builtin
        if isinstance(func, ast.Name):
            name = func.id
            if name in mod.functions:
                return internal((mod.path, name))
            if name in mod.classes:
                return internal((mod.path, f"{name}.__init__"))
            fi_entry = mod.from_imports.get(name)
            if fi_entry is not None:
                tgt_mod, ext, orig = fi_entry
                if ext is not None:
                    return external(f"{ext}.{orig}" if ext else orig)
                # tgt_mod == "" is the package ROOT __init__ — a valid
                # internal module, not an absent one
                target = (
                    self.module_for_dotted(tgt_mod)
                    if tgt_mod is not None
                    else None
                )
                if target is not None:
                    if orig in target.functions:
                        return internal((target.path, orig))
                    if orig in target.classes:
                        return internal(
                            (target.path, f"{orig}.__init__")
                        )
                    # re-export chase (package __init__)
                    fi2 = target.from_imports.get(orig)
                    if fi2 is not None and fi2[0] is not None:
                        t2 = self.module_for_dotted(fi2[0])
                        if t2 is not None:
                            if fi2[2] in t2.functions:
                                return internal((t2.path, fi2[2]))
                            if fi2[2] in t2.classes:
                                return internal(
                                    (t2.path, f"{fi2[2]}.__init__")
                                )
                return None
            # builtin or unknown bare name: report as external so the
            # taint pass can catch id()/float()/etc.
            return external(name)

        if not isinstance(func, ast.Attribute):
            return None

        dotted = _dotted(func)
        if not dotted:
            # something.method() on a non-name expression; try
            # `self.attr.m()` shape below via structure
            return self._resolve_attr_chain(
                mod, fi, func, class_attrs, lineno, col
            )
        parts = dotted.split(".")
        head, method = parts[0], parts[-1]

        # self.m() / cls.m()
        if head in ("self", "cls") and len(parts) == 2 and fi.class_name:
            key = self._method_key(mod, fi.class_name, method)
            if key is not None:
                return CallSite(key, None, lineno, col)
            return None

        # self.attr.m()
        if head == "self" and len(parts) == 3:
            attr_type = class_attrs.get(parts[1])
            if attr_type:
                key = self._method_key(mod, attr_type, method)
                if key is not None:
                    return CallSite(key, None, lineno, col)
            return None

        # x.m() where x has a locally inferred class type
        if len(parts) == 2 and head in local_types:
            key = self._method_key(mod, local_types[head], method)
            if key is not None:
                return CallSite(key, None, lineno, col)
            return None

        # g.m() where g is a module-level instance global with an
        # inferred class (ctor or `-> T`-annotated factory assignment)
        if len(parts) == 2 and head in mod.var_class:
            owner, cname = mod.var_class[head]
            key = self._method_key(owner, cname, method)
            if key is not None:
                return CallSite(key, None, lineno, col)
            return None

        # mod.f() through an import alias (possibly dotted alias)
        alias = mod.import_alias.get(head)
        if alias is not None:
            full = ".".join([alias] + parts[1:])
            prefix = self.pkg_name + "."
            if full.startswith(prefix) or alias == self.pkg_name:
                inner = full[len(prefix):] if full.startswith(prefix) else ""
                return self._resolve_internal_dotted(inner, lineno, col)
            return CallSite(None, full, lineno, col)

        # module object via from-import: `from ..crypto import merkle`
        fi_entry = mod.from_imports.get(head)
        if fi_entry is not None and fi_entry[0] is not None:
            base = (
                fi_entry[0] + "." + fi_entry[2]
                if fi_entry[0]
                else fi_entry[2]
            )
            target = self.module_for_dotted(base)
            if target is not None and len(parts) == 2:
                if method in target.functions:
                    return CallSite(
                        (target.path, method), None, lineno, col
                    )
                if method in target.classes:
                    return internal((target.path, f"{method}.__init__"))
                return None
            # class method through imported class: Cls.m()
            found = self.find_class(mod, head)
            if found is not None and len(parts) == 2:
                key = self._method_key(mod, head, method)
                if key is not None:
                    return CallSite(key, None, lineno, col)
            return None

        # ClassName.method() on a local class
        if head in mod.classes and len(parts) == 2:
            key = self._method_key(mod, head, method)
            if key is not None:
                return CallSite(key, None, lineno, col)
            return None

        # unknown receiver — external dotted name for catalog matching
        return CallSite(None, dotted, lineno, col)

    def _resolve_internal_dotted(
        self, inner: str, lineno: int, col: int
    ) -> Optional[CallSite]:
        """Resolve "types.vote.Vote" style fully-dotted internal refs."""
        if not inner:
            return None
        parts = inner.split(".")
        for split in range(len(parts) - 1, 0, -1):
            modname = ".".join(parts[:split])
            target = self.module_for_dotted(modname)
            if target is None:
                continue
            rest = parts[split:]
            if len(rest) == 1:
                if rest[0] in target.functions:
                    return CallSite(
                        (target.path, rest[0]), None, lineno, col
                    )
                if rest[0] in target.classes:
                    key = (target.path, f"{rest[0]}.__init__")
                    if key in self.functions:
                        return CallSite(key, None, lineno, col)
                    return None
            elif len(rest) == 2 and rest[0] in target.classes:
                key = self._method_key(target, rest[0], rest[1])
                if key is not None:
                    return CallSite(key, None, lineno, col)
            return None
        return None

    def _resolve_attr_chain(
        self, mod, fi, func, class_attrs, lineno, col
    ) -> Optional[CallSite]:
        # `self.conflicting_block.signed_header.hash()` — too dynamic;
        # give up (documented limitation)
        return None


def _class_name(rec: dict) -> str:
    return rec["node"].name


def _body_walk(fn: ast.AST):
    """Walk a function body WITHOUT descending into nested function or
    class definitions (they are separate graph nodes)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def build_package(root: Optional[str] = None) -> Package:
    from ..tmlint import package_root

    root = root or package_root()
    pkg = Package(root, os.path.basename(os.path.abspath(root)))
    pkg.build()
    return pkg
