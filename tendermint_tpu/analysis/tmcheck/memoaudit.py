"""Memo-soundness audit — the machine-checked argument behind every
cache in the hot path.

The warm-commit work (PERF.md "Warm path") rests on a family of memos:
commit-scoped sign-bytes rows, BlockIDFlag arrays, validator-set pubkey
bytes, proto wire bytes, merkle roots, and the commit-level
verification memo in crypto/sigcache. Each is sound only if the
memoized function is a PURE function of its inputs — no wall clock, no
RNG, no float arithmetic, no hash-order iteration can reach its body or
anything it calls. That is exactly the taint property tmcheck already
proves for the sign-bytes region; this module re-runs the same
interprocedural source scan with every MEMOIZED function as a root, so
"the memo is sound by construction" is a gate, not a comment.

Two checks:

1. **Catalog completeness** (`memo-uncataloged`): every function that
   both LOADS and STORES a memo-named attribute on the same receiver
   (`self._x_memo`, `self._hash`, `self.__dict__["_sb_memo"]`,
   `getattr(self, "_proto_memo", ...)` and friends) must appear in
   CATALOG below. A new memo cannot ship without declaring its
   soundness class.
2. **Taint cleanliness** (`memo-taint`): every catalog entry of kind
   "consensus" is used as a taint sink root — any nondeterminism
   source reachable from it (same catalogs, suppressions, and witness
   chains as the sign-bytes taint pass) is a violation. Entries of
   kind "identity" produce content-free identity tokens (their only
   output is a fresh `object()`), audited for catalog presence but
   exempt from the float/clock scan by declared justification.

`scripts/lint.py --memo-audit` prints the full listing (function,
memo attributes, declared inputs, taint status) and the full gate runs
both checks on every invocation. docs/static_analysis.md ("Memo
soundness") has the prose argument this module enforces.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..tmlint import Violation
from .callgraph import FuncInfo, Package, _body_walk, build_package
from .taint import _suppressed_lines, function_sources

__all__ = [
    "CATALOG",
    "MemoEntry",
    "audit",
    "discover_memoizers",
    "memo_audit_violations",
    "render_report",
]

# attribute names that hold memoized state but don't contain "memo"
_EXTRA_MEMO_ATTRS = {
    "_hash",
    "_sign_templates",
    "_sb_rows",
    "_sb_complete",
    "_fp_token",
    "_memo_epoch",
}


def _is_memo_attr(name: str) -> bool:
    # private-by-convention only: public attrs named e.g. `memory` are
    # state, not memos — every in-tree memo is underscore-prefixed
    return name.startswith("_") and (
        "memo" in name or name in _EXTRA_MEMO_ATTRS
    )


class MemoEntry:
    """One cataloged memoized function: where it lives, what makes its
    memo sound, and which audit it gets."""

    __slots__ = ("path", "qualname", "kind", "why")

    def __init__(self, path: str, qualname: str, kind: str, why: str):
        assert kind in ("consensus", "identity")
        self.path = path
        self.qualname = qualname
        self.kind = kind
        self.why = why


# The declared memo surface. "consensus": the memoized value feeds
# consensus-critical bytes or accept/reject decisions — must be
# taint-clean transitively. "identity": the function only mints or
# validates identity tokens (fresh object() / epoch pins) whose VALUE
# carries no data; catalog presence is still enforced so the
# invalidation protocol stays reviewed.
CATALOG: List[MemoEntry] = [
    MemoEntry(
        "types/commit.py", "Commit.vote_sign_bytes", "consensus",
        "sign-bytes row per (chain_id, index); inputs frozen after "
        "construction, dropped by the _MUT_EPOCH hook on any mutation",
    ),
    MemoEntry(
        "types/commit.py", "Commit.sign_bytes_batch", "consensus",
        "all sign-bytes rows per chain_id; same epoch invalidation",
    ),
    MemoEntry(
        "types/commit.py", "Commit._rows_for", "consensus",
        "allocator for the shared sign-bytes row lists",
    ),
    MemoEntry(
        "types/commit.py", "Commit._sign_template", "consensus",
        "splice template per (chain_id, for_block)",
    ),
    MemoEntry(
        "types/commit.py", "Commit.block_id_flags_array", "consensus",
        "uint8 BlockIDFlags; drives the vectorized tally masks",
    ),
    MemoEntry(
        "types/commit.py", "Commit.hash", "consensus",
        "merkle root over marshalled CommitSigs",
    ),
    MemoEntry(
        "types/commit.py", "Commit.fingerprint_token", "identity",
        "content-identity object for the commit-level sigcache memo; "
        "the token VALUE is meaningless — only replaced-on-mutation "
        "identity matters",
    ),
    MemoEntry(
        "types/commit.py", "Commit._memos_fresh", "identity",
        "epoch pin/clear checkpoint for every Commit memo",
    ),
    MemoEntry(
        "types/vote.py", "Vote.sign_bytes", "consensus",
        "canonical vote sign-bytes per chain_id; __setattr__ drops the "
        "memo on any encoded-field write",
    ),
    MemoEntry(
        "types/header.py", "Header.hash", "consensus",
        "field-merkle root; every Header field feeds the tree, so "
        "__setattr__ drops the memo on ANY attribute write (the "
        "dataclass __init__ included) — same discipline as "
        "Vote._SB_FIELDS",
    ),
    MemoEntry(
        "types/validator.py", "ValidatorSet.hash", "consensus",
        "merkle root over SimpleValidator leaves; cleared by _reindex",
    ),
    MemoEntry(
        "types/validator.py", "ValidatorSet.to_proto", "consensus",
        "wire bytes validated per call against a full fingerprint of "
        "the mutable inputs (ADVICE r5)",
    ),
    MemoEntry(
        "types/validator.py", "ValidatorSet.pubkeys_bytes", "consensus",
        "raw pubkey encodings for warm cache-key builds; cleared by "
        "_reindex and by the _VAL_MUT_EPOCH hook on in-place pub_key "
        "re-assignment",
    ),
    MemoEntry(
        "types/validator.py", "ValidatorSet.powers_array", "consensus",
        "voting powers for the vectorized tallies; cleared by _reindex "
        "and by the _VAL_MUT_EPOCH hook on in-place voting_power "
        "re-assignment, so it can never diverge from the scalar "
        "paths' live reads (ADVICE r5)",
    ),
    MemoEntry(
        "types/validator.py", "ValidatorSet.total_voting_power",
        "consensus",
        "threshold input; recomputed through _update_total_voting_power "
        "on every membership change",
    ),
    MemoEntry(
        "types/validator.py", "ValidatorSet.fingerprint_token",
        "identity",
        "membership-identity object for the commit-level sigcache memo; "
        "powers are fingerprinted separately with live bytes",
    ),
]


def discover_memoizers(
    pkg: Package,
) -> Dict[Tuple[str, str], Set[str]]:
    """(path, qualname) -> memo attribute names, for every function
    that both loads and stores a memo-named attribute on the same
    receiver. Recognized forms per receiver name R (usually `self`):

      store:  R.attr = ... | R.__dict__["attr"] = ...
      load:   R.attr | getattr(R, "attr", ...) | R.__dict__["attr"]
              | R.__dict__.get("attr", ...)

    Store-only functions (invalidators like _reindex, copiers writing a
    DIFFERENT receiver) are deliberately not memoizers."""
    out: Dict[Tuple[str, str], Set[str]] = {}
    for key, fi in pkg.functions.items():
        loads: Set[Tuple[str, str]] = set()
        stores: Set[Tuple[str, str]] = set()
        for node in _body_walk(fi.node):
            recv_attr = _attr_access(node)
            if recv_attr is None:
                continue
            recv, attr, is_store = recv_attr
            if not _is_memo_attr(attr):
                continue
            (stores if is_store else loads).add((recv, attr))
        both = {attr for (recv, attr) in loads if (recv, attr) in stores}
        if both:
            out[key] = both
    return out


def _attr_access(node: ast.AST) -> Optional[Tuple[str, str, bool]]:
    """(receiver name, attribute, is_store) when `node` is one of the
    recognized memo-attribute access forms, else None."""
    # R.attr (plain attribute load/store)
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return (
            node.value.id, node.attr, isinstance(node.ctx, ast.Store)
        )
    # R.__dict__["attr"] load/store
    if (
        isinstance(node, ast.Subscript)
        and isinstance(node.value, ast.Attribute)
        and node.value.attr == "__dict__"
        and isinstance(node.value.value, ast.Name)
        and isinstance(node.slice, ast.Constant)
        and isinstance(node.slice.value, str)
    ):
        return (
            node.value.value.id,
            node.slice.value,
            isinstance(node.ctx, ast.Store),
        )
    if isinstance(node, ast.Call):
        # getattr(R, "attr"[, default]) — load
        if (
            isinstance(node.func, ast.Name)
            and node.func.id == "getattr"
            and len(node.args) >= 2
            and isinstance(node.args[0], ast.Name)
            and isinstance(node.args[1], ast.Constant)
            and isinstance(node.args[1].value, str)
        ):
            return (node.args[0].id, node.args[1].value, False)
        # R.__dict__.get("attr"[, default]) — load
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "get"
            and isinstance(f.value, ast.Attribute)
            and f.value.attr == "__dict__"
            and isinstance(f.value.value, ast.Name)
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            return (f.value.value.id, node.args[0].value, False)
    return None


class MemoFinding:
    __slots__ = ("rule", "path", "qualname", "lineno", "message", "source")

    def __init__(self, rule, path, qualname, lineno, message, source=""):
        self.rule = rule
        self.path = path
        self.qualname = qualname
        self.lineno = lineno
        self.message = message
        self.source = source


def audit(pkg: Optional[Package] = None):
    """Run both checks. Returns (entries_report, findings) where
    entries_report is a list of dicts (one per catalog entry, with its
    discovered memo attrs, declared inputs, and taint status) for
    --memo-audit's listing, and findings is the violation list."""
    pkg = pkg or build_package()
    findings: List[MemoFinding] = []
    discovered = discover_memoizers(pkg)
    by_name = {(e.path, e.qualname): e for e in CATALOG}

    # 1. completeness: every discovered memoizer is cataloged
    for (path, qualname), attrs in sorted(discovered.items()):
        if (path, qualname) in by_name:
            continue
        fi = pkg.functions[(path, qualname)]
        findings.append(
            MemoFinding(
                "memo-uncataloged", path, qualname, fi.lineno,
                f"{qualname} memoizes {sorted(attrs)} but is not in "
                "tmcheck.memoaudit.CATALOG — declare its soundness "
                "class (consensus/identity) and justification",
            )
        )

    # ... and every cataloged function still exists (renames must not
    # silently drop a function out of the audit)
    report: List[dict] = []
    ok_lines = {
        path: _suppressed_lines(mod.lines, "taint-ok")
        for path, mod in pkg.modules.items()
    }
    break_lines = {
        path: _suppressed_lines(mod.lines, "taint-break")
        for path, mod in pkg.modules.items()
    }
    for entry in CATALOG:
        key = (entry.path, entry.qualname)
        fi = pkg.functions.get(key)
        row = {
            "function": f"{entry.path}:{entry.qualname}",
            "kind": entry.kind,
            "why": entry.why,
            "memo_attrs": sorted(discovered.get(key, ())),
            "inputs": _declared_inputs(fi) if fi is not None else [],
            "taint": "-",
        }
        if fi is None:
            findings.append(
                MemoFinding(
                    "memo-uncataloged", entry.path, entry.qualname, 0,
                    f"cataloged memoized function {entry.qualname} not "
                    f"found in {entry.path} — update the CATALOG after "
                    "renames/moves",
                )
            )
            row["taint"] = "MISSING"
            report.append(row)
            continue
        if entry.kind == "consensus":
            hits = _taint_from(pkg, key, ok_lines, break_lines)
            row["taint"] = "clean" if not hits else "TAINTED"
            for func, hit, chain in hits:
                findings.append(
                    MemoFinding(
                        "memo-taint", func.path, func.qualname,
                        hit.lineno,
                        f"{hit.detail} is reachable from memoized "
                        f"{entry.qualname} via: "
                        + " -> ".join(f.render() for f in chain),
                        _line_at(pkg, func.path, hit.lineno),
                    )
                )
        else:
            row["taint"] = f"exempt ({entry.kind})"
        report.append(row)
    return report, findings


def _declared_inputs(fi: FuncInfo) -> List[str]:
    args = fi.node.args
    names = [a.arg for a in args.posonlyargs + args.args]
    if args.vararg:
        names.append("*" + args.vararg.arg)
    names.extend(a.arg for a in args.kwonlyargs)
    if args.kwarg:
        names.append("**" + args.kwarg.arg)
    return names


def _line_at(pkg: Package, path: str, lineno: int) -> str:
    lines = pkg.modules[path].lines if path in pkg.modules else []
    return lines[lineno - 1].strip() if 1 <= lineno <= len(lines) else ""


def _taint_from(
    pkg: Package,
    root: Tuple[str, str],
    ok_lines: Dict[str, Set[int]],
    break_lines: Dict[str, Set[int]],
):
    """BFS from one memoized root over the call graph (same edge
    semantics and suppressions as taint.analyze), returning
    (function, SourceHit, witness chain) triples."""
    from collections import deque

    parents: Dict[Tuple[str, str], Optional[Tuple[str, str]]] = {root: None}
    queue = deque([root])
    while queue:
        key = queue.popleft()
        fi = pkg.functions[key]
        for site in fi.calls:
            if site.target is None or site.target not in pkg.functions:
                continue
            if site.lineno in break_lines.get(fi.path, ()):
                continue
            if site.target not in parents:
                parents[site.target] = key
                queue.append(site.target)
    out = []
    for key in parents:
        fi = pkg.functions[key]
        hits = function_sources(fi, pkg.modules[fi.path].lines)
        if not hits:
            continue
        chain: List[FuncInfo] = []
        cur: Optional[Tuple[str, str]] = key
        while cur is not None:
            chain.append(pkg.functions[cur])
            cur = parents[cur]
        chain.reverse()
        for hit in hits:
            if hit.lineno in ok_lines.get(fi.path, ()):
                continue
            out.append((fi, hit, chain))
    out.sort(key=lambda t: (t[0].path, t[1].lineno, t[1].rule))
    return out


def findings_to_violations(findings: List[MemoFinding]) -> List[Violation]:
    return [
        Violation(
            rule=f.rule,
            path=f.path,
            line=f.lineno,
            col=0,
            message=f.message,
            source=f.source,
        )
        for f in findings
    ]


def memo_audit_violations(pkg: Optional[Package] = None) -> List[Violation]:
    """Findings as tmlint Violations (fingerprint/baseline machinery
    compatible, though the memo audit ships with ZERO accepted debt —
    there is no baseline file; every finding fails the gate)."""
    pkg = pkg or build_package()
    _report, findings = audit(pkg)
    return findings_to_violations(findings)


def render_report(report: List[dict]) -> str:
    """The --memo-audit listing: every memoized function, its inputs,
    and its audit outcome."""
    lines = ["memoized-function audit (tmcheck.memoaudit.CATALOG):"]
    for row in report:
        lines.append(
            f"  {row['function']}  [{row['kind']}]  taint={row['taint']}"
        )
        if row["memo_attrs"]:
            lines.append(f"      memo attrs: {', '.join(row['memo_attrs'])}")
        if row["inputs"]:
            lines.append(f"      inputs: {', '.join(row['inputs'])}")
        lines.append(f"      why sound: {row['why']}")
    return "\n".join(lines)
