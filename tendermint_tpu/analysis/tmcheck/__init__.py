"""tmcheck — whole-program analyses on top of tmlint.

Two machine-checked invariants that were previously trust-me:

1. **Taint** (`taint.py` on the call graph from `callgraph.py`): no
   nondeterminism source (wall clock, unseeded RNG, float arithmetic,
   set iteration, `id()`, `os.urandom` outside keygen) is reachable,
   through any interprocedural call path, from the sign-bytes/hash
   construction region (`types/canonical.py`, `crypto/tmhash.py`,
   `crypto/merkle.py`, `encoding/proto.py`, and every
   to_proto/sign_bytes/hash_bytes/hash in `types/`). Findings carry
   the full offending call chain; accepted debt lives in a counted
   fingerprint baseline (`taint_baseline.json`) and reviewed
   exceptions are in-file `# tmcheck: taint-ok` / `taint-break`
   suppressions.

2. **Wire schema** (`schema.py`): the statically-extracted
   (tag, wire type, order, repeated/conditional) table of every
   encoder plus each decoder's parsed-tag set, diffed against the
   golden `schema.json` and checked for encode/decode symmetry and
   ascending-tag emission. Any drift is a tier-1 failure;
   `scripts/lint.py --schema-update` is the reviewed update path.

3. **Memo audit** (`memoaudit.py`): every memoized function in the hot
   path (commit-scoped sign-bytes rows, flags arrays, validator-set
   pubkey bytes/wire bytes/roots — the machinery behind the warm
   commit path and the commit-level sigcache memo) is enumerated in a
   reviewed catalog and re-proved taint-clean with the same
   interprocedural source scan, so "the memo is sound by construction"
   is a gate. Uncataloged memo writers and taint-reachable memoized
   functions both fail; `scripts/lint.py --memo-audit` prints the full
   listing.

Run them via `scripts/lint.py` (--taint / --schema / --memo-audit) or
the tier-1 gates in tests/test_tmcheck.py. docs/static_analysis.md
documents the source/sink catalogs and the
suppression/baseline/golden policies.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ..tmlint import (
    Violation,
    load_baseline,
    new_violations,
    save_baseline,
)
from . import callgraph, memoaudit, schema, taint
from .callgraph import Package, build_package
from .memoaudit import memo_audit_violations
from .schema import (
    GOLDEN_PATH,
    extract_package,
    load_golden,
    save_golden,
    schema_violations,
)
from .taint import analyze as taint_analyze
from .taint import taint_violations

__all__ = [
    "Package",
    "RULES",
    "build_package",
    "taint_analyze",
    "taint_violations",
    "new_taint_violations",
    "memo_audit_violations",
    "schema_violations",
    "extract_package",
    "load_golden",
    "save_golden",
    "update_schema_golden",
    "update_taint_baseline",
    "TAINT_BASELINE_PATH",
    "GOLDEN_PATH",
]

TAINT_BASELINE_PATH = os.path.join(
    os.path.dirname(__file__), "taint_baseline.json"
)

# the tmcheck rule catalog — the single source --list-rules and the
# docs table mirror (ids are emitted by taint.py / schema.py)
RULES = [
    (
        "taint-wallclock",
        "wall-clock read reachable from sign-bytes/hash construction",
    ),
    (
        "taint-random",
        "unseeded RNG / OS entropy reachable from sign-bytes/hash "
        "construction",
    ),
    (
        "taint-float",
        "float arithmetic reachable from sign-bytes/hash construction",
    ),
    (
        "taint-set-iter",
        "set iteration reachable from sign-bytes/hash construction",
    ),
    (
        "taint-id",
        "id() reachable from sign-bytes/hash construction",
    ),
    (
        "schema-drift",
        "extracted wire schema differs from the golden schema.json",
    ),
    (
        "schema-order",
        "non-ascending field emission order in an encoder",
    ),
    (
        "schema-symmetry",
        "field written but not parsed (or parsed but not written)",
    ),
    (
        "memo-uncataloged",
        "memoizing function missing from the reviewed memo catalog "
        "(tmcheck.memoaudit.CATALOG)",
    ),
    (
        "memo-taint",
        "nondeterminism source reachable from a memoized function "
        "(a memo over a non-pure computation is unsound)",
    ),
]


def new_taint_violations(
    pkg: Optional[Package] = None,
    baseline_path: Optional[str] = None,
) -> List[Violation]:
    """Taint findings beyond the checked-in baseline (same counted
    fingerprint semantics as tmlint)."""
    violations = taint_violations(pkg)
    baseline = load_baseline(baseline_path or TAINT_BASELINE_PATH)
    return new_violations(violations, baseline)


def update_taint_baseline(
    pkg: Optional[Package] = None,
    baseline_path: Optional[str] = None,
) -> Dict[str, int]:
    return save_baseline(
        taint_violations(pkg), baseline_path or TAINT_BASELINE_PATH
    )


def update_schema_golden(
    root: Optional[str] = None,
    path: Optional[str] = None,
    pkg: Optional[Package] = None,
) -> dict:
    messages, _ = extract_package(root, pkg=pkg)
    return save_golden(messages, path)
