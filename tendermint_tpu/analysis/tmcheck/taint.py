"""Interprocedural nondeterminism-taint analysis.

The invariant (SURVEY.md "Determinism & safety", docs/static_analysis.md):
every byte that enters sign-bytes or a consensus hash must be
replica-identical. tmlint enforces that *syntactically inside* the
consensus-critical modules; this pass enforces it *transitively*: no
function reachable by calls from the sign-bytes/hash construction
region may contain a nondeterminism source.

Sink roots (where the protected byte streams are assembled):
- every function in `types/canonical.py` (canonical sign-bytes),
  `crypto/tmhash.py`, `crypto/merkle.py` (hash leaves/inner nodes),
  and `encoding/proto.py` (the ProtoWriter all encoders feed);
- every `to_proto` / `to_proto_bytes` / `sign_bytes` / `hash_bytes` /
  `hash` function or method in `types/` (the encode direction — what
  replicas hash and sign).

Sources (what must never be reachable from a root):
- wall-clock reads (`time.time`, `time.time_ns`, `datetime.now`, ...)
- unseeded/global RNG (`random.*` module functions) and OS entropy
  (`uuid1/4`, `secrets.*`); `os.urandom` outside the key-generation
  modules
- float arithmetic: float literals, `/` true division, `float()`
- set iteration (order is PYTHONHASHSEED-dependent); dict iteration is
  insertion-ordered in CPython >= 3.7 and deliberately exempt — the
  codebase relies on that, same call as tmlint's det-set-iter
- `id()` (per-process addresses)

Suppressions (both require an in-file justification, policy in
docs/static_analysis.md):
- `# tmcheck: taint-ok — why` on (or in the comment block above) a
  source line: the value provably never enters the protected bytes
  (e.g. telemetry attributes).
- `# tmcheck: taint-break — why` on a call line: taint does not
  propagate through THIS edge (e.g. a tracing span whose timings go to
  the metrics ring, never into the hash input).

Remaining accepted findings live in a counted, content-fingerprinted
baseline (taint_baseline.json) exactly like tmlint's.
"""

from __future__ import annotations

import ast
import re
from collections import deque
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..tmlint import Violation, dotted_name
from .callgraph import CallSite, FuncInfo, Package, _body_walk, build_package

__all__ = [
    "SourceHit",
    "TaintFinding",
    "analyze",
    "taint_violations",
    "SINK_ROOT_MODULES",
    "SINK_ROOT_NAMES",
]

# ---------------------------------------------------------------------------
# catalogs

_WALLCLOCK = {
    "time.time",
    "time.time_ns",
    "datetime.now",
    "datetime.utcnow",
    "datetime.today",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.datetime.today",
    "datetime.date.today",
    "date.today",
}

_RANDOM_MODULE_FNS = {
    "random",
    "choice",
    "choices",
    "shuffle",
    "sample",
    "randint",
    "randrange",
    "getrandbits",
    "uniform",
    "betavariate",
    "gauss",
    "normalvariate",
    "expovariate",
    "triangular",
    "randbytes",
}

_ENTROPY = {
    "uuid.uuid1",
    "uuid.uuid4",
    "secrets.token_bytes",
    "secrets.token_hex",
    "secrets.randbits",
    "secrets.randbelow",
    "secrets.choice",
}

# os.urandom is legitimate exactly where keys and nonces are born
KEYGEN_MODULES = (
    "crypto/keys.py",
    "crypto/ed25519.py",
    "crypto/sr25519.py",
    "crypto/secp256k1.py",
    "crypto/aead.py",
    "crypto/merlin.py",
)

# where the protected byte streams are assembled
SINK_ROOT_MODULES = (
    "types/canonical.py",
    "crypto/tmhash.py",
    "crypto/merkle.py",
    "encoding/proto.py",
)
SINK_ROOT_NAMES = (
    "to_proto",
    "to_proto_bytes",
    "sign_bytes",
    "hash_bytes",
    "hash",
)

_SUPPRESS_RE = re.compile(r"#\s*tmcheck:\s*(taint-ok|taint-break)\b")


# ---------------------------------------------------------------------------
# source detection


class SourceHit:
    __slots__ = ("rule", "lineno", "detail")

    def __init__(self, rule: str, lineno: int, detail: str) -> None:
        self.rule = rule
        self.lineno = lineno
        self.detail = detail


def _suppressed_lines(lines: List[str], kind: str) -> Set[int]:
    """1-based line numbers carrying `# tmcheck: <kind>` — on the line
    itself, or covering the first code line below a comment block
    (same convention as tmlint suppressions)."""
    out: Set[int] = set()
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m or m.group(1) != kind:
            continue
        out.add(i)
        if text.lstrip().startswith("#"):
            j = i + 1
            while j <= len(lines) and (
                not lines[j - 1].strip()
                or lines[j - 1].lstrip().startswith("#")
            ):
                j += 1
            if j <= len(lines):
                out.add(j)
    return out


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        return dotted_name(node.func) in ("set", "frozenset")
    return False


def _classify_external(name: str, path: str) -> Optional[Tuple[str, str]]:
    """(rule, detail) when a resolved external call is a source."""
    if name in _WALLCLOCK:
        return ("taint-wallclock", f"wall-clock read `{name}()`")
    if name in _ENTROPY:
        return ("taint-random", f"OS-entropy call `{name}()`")
    if name == "os.urandom" and not path.startswith(KEYGEN_MODULES):
        return ("taint-random", "`os.urandom()` outside keygen modules")
    parts = name.split(".")
    if (
        len(parts) == 2
        and parts[0] in ("random", "_random")
        and parts[1] in _RANDOM_MODULE_FNS
    ):
        return ("taint-random", f"unseeded global RNG `{name}()`")
    if name == "id":
        return ("taint-id", "`id()` is a per-process address")
    if name == "float":
        return ("taint-float", "`float()` conversion")
    return None


def function_sources(fi: FuncInfo, lines: List[str]) -> List[SourceHit]:
    """Nondeterminism sources syntactically inside one function body
    (nested defs excluded), before suppression filtering."""
    hits: List[SourceHit] = []
    set_names: Set[str] = set()
    for node in _body_walk(fi.node):
        if isinstance(node, ast.Assign) and _is_set_expr(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    set_names.add(tgt.id)
    for node in _body_walk(fi.node):
        if isinstance(node, ast.Constant) and isinstance(node.value, float):
            hits.append(
                SourceHit(
                    "taint-float", node.lineno, f"float literal `{node.value!r}`"
                )
            )
        elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Div):
            hits.append(
                SourceHit(
                    "taint-float",
                    node.lineno,
                    "true division `/` produces a float",
                )
            )
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            it = node.iter
            if _is_set_expr(it) or (
                isinstance(it, ast.Name) and it.id in set_names
            ):
                hits.append(
                    SourceHit(
                        "taint-set-iter",
                        node.lineno,
                        "iteration over a set (hash-order dependent)",
                    )
                )
        elif isinstance(
            node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
        ):
            for gen in node.generators:
                it = gen.iter
                if _is_set_expr(it) or (
                    isinstance(it, ast.Name) and it.id in set_names
                ):
                    hits.append(
                        SourceHit(
                            "taint-set-iter",
                            node.lineno,
                            "comprehension over a set (hash-order dependent)",
                        )
                    )
    # external source calls come from resolved CallSites so import
    # aliasing can't hide them
    for site in fi.calls:
        if site.external:
            cls = _classify_external(site.external, fi.path)
            if cls is not None:
                hits.append(SourceHit(cls[0], site.lineno, cls[1]))
    return hits


# ---------------------------------------------------------------------------
# reachability


class TaintFinding:
    """One source site reachable from a sink root, with the witness
    call chain (shortest, by BFS)."""

    __slots__ = ("hit", "func", "chain")

    def __init__(
        self, hit: SourceHit, func: FuncInfo, chain: List[FuncInfo]
    ) -> None:
        self.hit = hit
        self.func = func
        self.chain = chain  # [root, ..., func]

    def render_chain(self) -> str:
        return " -> ".join(f.render() for f in self.chain)


def _is_sink_root(fi: FuncInfo) -> bool:
    if fi.path in SINK_ROOT_MODULES:
        return True
    if fi.path.startswith("types/"):
        leaf = fi.qualname.split(".")[-1]
        return leaf in SINK_ROOT_NAMES
    return False


def analyze(pkg: Optional[Package] = None) -> List[TaintFinding]:
    pkg = pkg or build_package()
    lines_by_path: Dict[str, List[str]] = {
        path: mod.lines for path, mod in pkg.modules.items()
    }
    break_lines: Dict[str, Set[int]] = {
        path: _suppressed_lines(lines, "taint-break")
        for path, lines in lines_by_path.items()
    }
    ok_lines: Dict[str, Set[int]] = {
        path: _suppressed_lines(lines, "taint-ok")
        for path, lines in lines_by_path.items()
    }

    # multi-source BFS from every sink root, shortest chains
    parents: Dict[Tuple[str, str], Optional[Tuple[str, str]]] = {}
    queue: deque = deque()
    for key, fi in pkg.functions.items():
        if _is_sink_root(fi):
            parents[key] = None
            queue.append(key)
    while queue:
        key = queue.popleft()
        fi = pkg.functions[key]
        for site in fi.calls:
            if site.target is None or site.target not in pkg.functions:
                continue
            if site.lineno in break_lines.get(fi.path, ()):
                continue
            if site.target not in parents:
                parents[site.target] = key
                queue.append(site.target)

    findings: List[TaintFinding] = []
    for key in parents:
        fi = pkg.functions[key]
        hits = function_sources(fi, lines_by_path.get(fi.path, []))
        if not hits:
            continue
        chain: List[FuncInfo] = []
        cur: Optional[Tuple[str, str]] = key
        while cur is not None:
            chain.append(pkg.functions[cur])
            cur = parents[cur]
        chain.reverse()
        for hit in hits:
            if hit.lineno in ok_lines.get(fi.path, ()):
                continue
            findings.append(TaintFinding(hit, fi, chain))
    findings.sort(
        key=lambda f: (f.func.path, f.hit.lineno, f.hit.rule)
    )
    return findings


def taint_violations(pkg: Optional[Package] = None) -> List[Violation]:
    """Findings as tmlint Violations so the fingerprint/baseline
    machinery applies unchanged. The fingerprint covers the SOURCE
    line only (rule:path:sha1(line)) — chains shift with unrelated
    refactors, offending lines don't."""
    pkg = pkg or build_package()
    out: List[Violation] = []
    for f in analyze(pkg):
        lines = pkg.modules[f.func.path].lines
        text = (
            lines[f.hit.lineno - 1].strip()
            if 1 <= f.hit.lineno <= len(lines)
            else ""
        )
        out.append(
            Violation(
                rule=f.hit.rule,
                path=f.func.path,
                line=f.hit.lineno,
                col=0,
                message=(
                    f"{f.hit.detail} is reachable from sign-bytes/hash "
                    f"construction via: {f.render_chain()}"
                ),
                source=text,
            )
        )
    return out
