"""Attacker-input entry derivation for tmsafe.

The whole point of the gate is that its source catalog cannot rot by
hand: the entries are machine-derived from the same extraction that
pins the wire protocol.

Entry families (each yields (FuncInfo key, tainted params, rule mask)):

1. **Wire decoders** — every decoder tmcheck's schema extraction finds
   (the same extraction whose output is pinned golden in
   `analysis/tmcheck/schema.json`): all 90+ `from_proto`/`decode_*`
   functions across types/, abci/codec, the reactor codecs, crypto
   keys and merkle proofs. Every non-self parameter is attacker bytes.
2. **RPC/WS param parsing** — every function in the package with an
   `RPCRequest`-annotated parameter (the JSON-RPC route handlers in
   rpc/core.py), plus the server-side parse functions in
   rpc/jsonrpc.py that turn raw HTTP/WS bytes into request objects.
3. **WAL reads** — the consensus WAL replay iterators. A WAL is
   written locally, but replay-after-crash must tolerate torn/corrupt
   records, and statesync'd nodes replay files they did not write;
   the bytes are treated as hostile like any wire input.
4. **P2P framing** — functions in the connection/transport layer that
   consume socket bytes (`recv`/`read`/`readexactly` results), before
   any message-level decode runs.
5. **Message validators** — every `validate_basic` in the package.
   These run BEFORE signature checks on attacker messages, so their
   loop structure is attacker-amplifiable; they participate in the
   quadratic-decode rule only (their field values are checked by the
   very comparisons the taint rules would misread as unsanitized
   sources, so alloc/index taint is owned by the decode entries).

Taint kinds (see taintflow.py): every decoder byte parameter seeds as
LEN taint (attacker-chosen content, but its size is already capped by
the transport's MAX_MSG_SIZE / MAX_FRAME before the decoder runs);
VAL taint — unbounded attacker-chosen integers — is born at the parse
primitives (decode_varint, FieldReader int accessors, iter_fields
values), not at the entries.
"""

from __future__ import annotations

import re
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ..tmcheck.callgraph import FuncInfo, Package
from ..tmcheck.schema import extract_package

__all__ = [
    "Entry",
    "RULE_TAINT",
    "RULE_QUADRATIC",
    "RULE_ALL",
    "derive_entries",
    "P2P_FRAMING_MODULES",
    "RPC_PARSE_FUNCS",
    "WAL_ENTRY_FUNCS",
]

FuncKey = Tuple[str, str]

# rule-participation mask
RULE_TAINT = 1  # safe-alloc-unbounded + safe-index-unchecked
RULE_QUADRATIC = 2  # safe-quadratic-decode
RULE_ALL = RULE_TAINT | RULE_QUADRATIC

# socket-byte consumers: every function in these modules that binds a
# `.recv(...)` / `.read(...)` / `.readexactly(...)` result handles raw
# peer bytes before any decoder runs
P2P_FRAMING_MODULES = ("p2p/conn.py", "p2p/transport.py")

# the server-side HTTP/WS parse path in rpc/jsonrpc.py: raw body/query
# bytes -> params dict (the route handlers themselves are found by
# their RPCRequest annotation)
RPC_PARSE_FUNCS = (
    "JSONRPCServer._handle_post_body",
    "JSONRPCServer._handle_uri",
    "JSONRPCServer._dispatch_obj",
)

WAL_ENTRY_FUNCS = (
    ("consensus/wal.py", "iter_wal_records"),
    ("consensus/wal.py", "iter_wal_group"),
)

_READ_ATTRS = {"recv", "read", "readexactly", "recv_into"}


class Entry:
    """One attacker-input entry point."""

    __slots__ = ("key", "tainted_params", "rules", "family")

    def __init__(
        self,
        key: FuncKey,
        tainted_params: FrozenSet[str],
        rules: int,
        family: str,
    ) -> None:
        self.key = key
        self.tainted_params = tainted_params
        self.rules = rules
        self.family = family

    def render(self) -> str:
        return f"{self.key[0]}:{self.key[1]} [{self.family}]"


def _fn_params(fi: FuncInfo) -> List[str]:
    args = fi.node.args
    names = [a.arg for a in args.posonlyargs + args.args]
    if args.vararg:
        names.append(args.vararg.arg)
    names.extend(a.arg for a in args.kwonlyargs)
    return [n for n in names if n not in ("self", "cls")]

import ast  # noqa: E402  (used below; kept near first use for clarity)


def _annotated_params(fi: FuncInfo, type_name: str) -> List[str]:
    """Parameter names annotated with `type_name` (bare or quoted)."""
    out: List[str] = []
    args = fi.node.args
    for a in args.posonlyargs + args.args + args.kwonlyargs:
        ann = a.annotation
        name = ""
        if isinstance(ann, ast.Name):
            name = ann.id
        elif isinstance(ann, ast.Attribute):
            name = ann.attr
        elif isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            name = ann.value.strip("'\"").split("[")[0].split(".")[-1]
        elif isinstance(ann, ast.Subscript):
            # Optional[RPCRequest] etc.
            inner = ann.slice
            if isinstance(inner, ast.Name):
                name = inner.id
        if name == type_name:
            out.append(a.arg)
    return out


def _schema_decoder_keys(pkg: Package) -> List[FuncKey]:
    """(path, qualname) of every decoder the wire-schema extraction
    recognizes — the machine-derived core of the source catalog."""
    messages, _ = extract_package(pkg.root, pkg=pkg)
    keys: Set[FuncKey] = set()
    for mkey, msg in messages.items():
        if not msg.dec_func:
            continue
        path, _, tail = mkey.partition("::")
        # class-paired messages: "types/vote.py::Vote" + dec "from_proto"
        # -> Vote.from_proto; module-level: decode function by own name
        cand = [f"{tail}.{msg.dec_func}", msg.dec_func]
        # encode-only suffixed keys ("::Cls.hash_bytes") never decode
        for qual in cand:
            if (path, qual) in pkg.functions:
                keys.add((path, qual))
                break
    return sorted(keys)


_VALIDATE_RE = re.compile(r"(^|\.)validate_basic$")


def derive_entries(pkg: Package) -> List[Entry]:
    entries: Dict[FuncKey, Entry] = {}

    def add(key, params, rules, family):
        if key in entries:
            old = entries[key]
            entries[key] = Entry(
                key,
                old.tainted_params | frozenset(params),
                old.rules | rules,
                old.family,
            )
        else:
            entries[key] = Entry(key, frozenset(params), rules, family)

    # 1. wire decoders (schema-derived)
    for key in _schema_decoder_keys(pkg):
        fi = pkg.functions[key]
        add(key, _fn_params(fi), RULE_ALL, "decoder")

    # 2a. RPC route handlers: RPCRequest-annotated params, anywhere
    for key, fi in pkg.functions.items():
        params = _annotated_params(fi, "RPCRequest")
        if params:
            add(key, params, RULE_ALL, "rpc")

    # 2b. the raw HTTP/WS parse path
    for qual in RPC_PARSE_FUNCS:
        key = ("rpc/jsonrpc.py", qual)
        if key in pkg.functions:
            add(key, _fn_params(pkg.functions[key]), RULE_ALL, "rpc-parse")

    # 3. WAL replay iterators
    for key in WAL_ENTRY_FUNCS:
        if key in pkg.functions:
            add(key, _fn_params(pkg.functions[key]), RULE_ALL, "wal")

    # 4. p2p framing: any function in the framing modules that binds a
    # socket-read result (the taint engine seeds those results too;
    # listing the function as an entry puts it in the scanned region)
    for key, fi in pkg.functions.items():
        if fi.path not in P2P_FRAMING_MODULES:
            continue
        if _binds_socket_read(fi):
            add(key, (), RULE_ALL, "p2p-framing")

    # 5. validators: quadratic-decode scope only, `self` tainted
    for key, fi in pkg.functions.items():
        if _VALIDATE_RE.search(fi.qualname):
            add(key, ("self",), RULE_QUADRATIC, "validate")

    return [entries[k] for k in sorted(entries)]


def _binds_socket_read(fi: FuncInfo) -> bool:
    from ..tmcheck.callgraph import _body_walk

    for node in _body_walk(fi.node):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _READ_ATTRS
        ):
            return True
    return False
