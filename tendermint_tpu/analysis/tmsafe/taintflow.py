"""Attacker-taint dataflow: decode-bound allocation, unchecked
indexing, and tainted recursion over the wire-input region.

Two taint kinds, because the bound matters more than the bit:

- **LEN** — attacker-chosen *content* whose size is already capped by
  the byte stream it arrived in (the transport rejects frames over
  MAX_MSG_SIZE before any decoder runs). Copying, slicing, or hashing
  LEN data is work proportional to bytes the peer actually sent —
  self-limiting, never flagged.
- **VAL** — an unbounded attacker-chosen *integer*: the result of
  parsing a varint/fixed field, `int()` of attacker text, or a
  JSON-decoded number. Ten wire bytes encode 2**63; any allocation or
  loop bound derived from VAL without a clamp is an asymmetric-cost
  lever (amplification in the arxiv 2302.00418 sense: one cheap
  message, unbounded server work).

VAL is born at the parse primitives (`decode_varint`, FieldReader int
accessors, `iter_fields` values, `struct.unpack`, `json.loads`), not
at the entries — entry byte parameters seed as LEN.

Sinks (rules):
- `safe-alloc-unbounded`: `bytes(v)` / `bytearray(v)` / sequence
  repetition `lit * v` / `range(v)` loop bounds with VAL `v`; plus
  recursion on tainted input (stack is an allocation too).
- `safe-index-unchecked`: a plain (non-slice) subscript whose index is
  VAL — in Python that is not memory-unsafe but it IS
  attacker-steered aliasing: an int64 field is signed, so `-1` reads
  the *last* element with no error raised. Slices are exempt by
  design: Python slices clamp, and the result is bounded by the
  source's length.

Sanitizers (what turns VAL back off):
- a comparison (`if`/`while`/`assert`/ternary test) between the
  tainted name and any untainted expression — the in-tree `MAX_*`
  constants, int literals, `len(...)` calls, `.size()` results. After
  the test the name is clean for the rest of the function (lexical,
  not path-sensitive: the codebase's universal idiom is
  guard-then-raise).
- `min(v, bound)` — the clamp expression itself.
- an enclosing `try` that catches IndexError/KeyError/LookupError
  (or everything) sanitizes index sinks inside it: the decoder's
  deliberate probe-and-translate idiom.
- `% nonzero-untainted` bounds the value.

The interprocedural half is a monotone fixpoint over the PR-5 call
graph: one merged context per function (joined parameter taint),
return-taint summaries propagated caller-ward until stable. Taint
does not flow through object attributes across functions (a decoded
message handed to a handler is the validate-before-use gate's job,
not this pass's) nor into nested `def`s; both under-approximations
are documented in docs/static_analysis.md.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..tmcheck.callgraph import (
    CallSite,
    FuncInfo,
    Package,
    _body_walk,
)
from . import amplify
from .sources import Entry, RULE_QUADRATIC, RULE_TAINT

__all__ = ["TaintEngine", "Finding", "NONE", "LEN", "VAL"]

FuncKey = Tuple[str, str]

NONE = 0
LEN = 1
VAL = 2

# FieldReader accessors by result kind
_READER_INT = {"uint", "int64", "sfixed64"}
_READER_LEN = {"bytes", "string", "get"}
_READER_VAL_COLLECTION = {"get_all"}

# parse primitives that mint VAL from LEN bytes
_PARSE_VAL_FNS = {
    "decode_varint",
    "decode_zigzag",
    "iter_fields",
}
# wrappers that re-bound their result internally
_PARSE_LEN_FNS = {"read_length_prefixed"}

# the one shared catalog of socket/file read methods: sources.py uses
# it to discover p2p-framing entries, the engine to seed/check reads —
# a single set so the entry region and the taint model cannot drift
from .sources import _READ_ATTRS as _SOCKET_READ_ATTRS  # noqa: E402

# external calls whose result is bounded regardless of args
_CLEAN_EXTERNALS = {
    "str",
    "repr",
    "bool",
    "float",
    "hex",
    "isinstance",
    "hasattr",
    "getattr",
    "print",
    "type",
    "format",
}

# exception names whose handlers sanitize index sinks inside the try:
# they actually CATCH IndexError. `except ValueError` deliberately
# does NOT qualify — it would not catch the IndexError, and a negative
# wire index raises nothing at all (the aliasing the rule exists for)
_INDEX_GUARD_EXCS = {
    "IndexError",
    "KeyError",
    "LookupError",
    "Exception",
    "BaseException",
}


class Finding:
    __slots__ = ("rule", "path", "lineno", "col", "detail", "key")

    def __init__(self, rule, path, lineno, col, detail, key):
        self.rule = rule
        self.path = path
        self.lineno = lineno
        self.col = col
        self.detail = detail
        self.key = key  # FuncKey where the sink sits


class _FnState:
    """Per-function joined analysis state."""

    __slots__ = ("param_taint", "ret", "rules", "analyzed")

    def __init__(self) -> None:
        self.param_taint: Dict[str, int] = {}
        self.ret: int = NONE
        self.rules: int = 0
        self.analyzed = False


class TaintEngine:
    def __init__(self, pkg: Package, entries: List[Entry]) -> None:
        self.pkg = pkg
        self.entries = entries
        self.states: Dict[FuncKey, _FnState] = {}
        self.callers: Dict[FuncKey, Set[FuncKey]] = {}
        self.parent: Dict[FuncKey, Tuple[FuncKey, int]] = {}
        self.findings: Dict[Tuple[str, str, int, int], Finding] = {}
        self._work: List[FuncKey] = []
        self._queued: Set[FuncKey] = set()

    # -- public --

    def run(self) -> List[Finding]:
        for e in self.entries:
            if e.key not in self.pkg.functions:
                continue
            st = self._state(e.key)
            st.rules |= e.rules
            for p in e.tainted_params:
                st.param_taint[p] = max(st.param_taint.get(p, NONE), LEN)
            self._enqueue(e.key)
        while self._work:
            key = self._work.pop()
            self._queued.discard(key)
            self._analyze(key)
        out = sorted(
            self.findings.values(),
            key=lambda f: (f.path, f.lineno, f.col, f.rule),
        )
        return out

    def chain(self, key: FuncKey) -> List[str]:
        """Entry -> ... -> key witness (function identities)."""
        seen: Set[FuncKey] = set()
        chain: List[str] = []
        cur: Optional[FuncKey] = key
        while cur is not None and cur not in seen:
            seen.add(cur)
            fi = self.pkg.functions.get(cur)
            chain.append(fi.render() if fi else f"{cur[0]}:{cur[1]}")
            nxt = self.parent.get(cur)
            cur = nxt[0] if nxt else None
        chain.reverse()
        return chain

    # -- machinery --

    def _state(self, key: FuncKey) -> _FnState:
        st = self.states.get(key)
        if st is None:
            st = _FnState()
            self.states[key] = st
        return st

    def _enqueue(self, key: FuncKey) -> None:
        if key not in self._queued:
            self._queued.add(key)
            self._work.append(key)

    def _flow_into(
        self,
        caller: FuncKey,
        callee: FuncKey,
        taints: Dict[str, int],
        rules: int,
        lineno: int,
    ) -> int:
        """Join `taints` into callee's params; (re)enqueue on growth.
        Returns the callee's current return summary."""
        st = self._state(callee)
        grew = False
        for name, kind in taints.items():
            if kind > st.param_taint.get(name, NONE):
                st.param_taint[name] = kind
                grew = True
        if rules & ~st.rules:
            st.rules |= rules
            grew = True
        if grew or not st.analyzed:
            self.parent.setdefault(callee, (caller, lineno))
            self._enqueue(callee)
        self.callers.setdefault(callee, set()).add(caller)
        return st.ret

    def _ret_update(self, key: FuncKey, ret: int) -> None:
        st = self._state(key)
        if ret > st.ret:
            st.ret = ret
            for c in self.callers.get(key, ()):
                self._enqueue(c)

    def report(self, rule, key, node, detail) -> None:
        fi = self.pkg.functions[key]
        k = (rule, fi.path, node.lineno, node.col_offset)
        if k not in self.findings:
            self.findings[k] = Finding(
                rule, fi.path, node.lineno, node.col_offset, detail, key
            )

    def _analyze(self, key: FuncKey) -> None:
        fi = self.pkg.functions.get(key)
        if fi is None:
            return
        st = self._state(key)
        st.analyzed = True
        walker = _BodyWalker(self, fi, st)
        walker.run()
        self._ret_update(key, walker.ret)


class _BodyWalker:
    """One function body, statements in program order, operands always
    evaluated (never short-circuited — a stack-order walk produced a
    vacuously-clean gate once already, see tests/test_tmtrace.py)."""

    def __init__(self, eng: TaintEngine, fi: FuncInfo, st: _FnState) -> None:
        self.eng = eng
        self.fi = fi
        self.key = fi.key
        self.rules = st.rules
        self.env: Dict[str, int] = dict(st.param_taint)
        self.sanitized: Set[str] = set()
        self.set_names: Set[str] = set()
        # locals bound to non-empty all-constant container literals —
        # a fixed membership universe (`names = {1: "ed25519", ...}`),
        # as opposed to a growing accumulator (`seen = []`)
        self.fixed_containers: Set[str] = set()
        # locals that are dicts (literal, dict() call, or dict/Dict
        # annotation): subscripting a dict cannot negative-alias
        # (KeyError is a sanctioned error, there is no index
        # arithmetic) and membership is O(1) — both rules exempt them
        self.dict_names: Set[str] = set()
        self.ret: int = NONE
        self.index_guard = 0
        self.loops: List[amplify.LoopFrame] = []
        self.sites: Dict[Tuple[int, int], CallSite] = {
            (s.lineno, s.col): s for s in fi.calls
        }

    def run(self) -> None:
        # two passes over loop bodies happen inside stmt(); the body
        # itself runs once (top-level straight-line code)
        for node in self.fi.node.body:
            self.stmt(node)

    # -- helpers --

    def _taint_of_name(self, name: str) -> int:
        if name in self.sanitized:
            return NONE
        return self.env.get(name, NONE)

    def _assign_name(self, name: str, kind: int) -> None:
        self.sanitized.discard(name)
        if kind:
            self.env[name] = kind
        else:
            self.env.pop(name, None)

    def _assign_target(self, tgt: ast.AST, kind: int, value=None) -> None:
        if isinstance(tgt, ast.Name):
            if value is not None and _is_set_expr(value):
                self.set_names.add(tgt.id)
            else:
                self.set_names.discard(tgt.id)
            if value is not None and _is_fixed_literal(value):
                self.fixed_containers.add(tgt.id)
            else:
                self.fixed_containers.discard(tgt.id)
            if value is not None and _is_dict_expr(value):
                self.dict_names.add(tgt.id)
            elif value is not None:
                self.dict_names.discard(tgt.id)
            self._assign_name(tgt.id, kind)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            parts = None
            if isinstance(value, (ast.Tuple, ast.List)) and len(
                value.elts
            ) == len(tgt.elts):
                parts = value.elts
            for i, elt in enumerate(tgt.elts):
                if parts is not None:
                    self._assign_target(elt, self.expr(parts[i]))
                else:
                    self._assign_target(elt, kind)
        elif isinstance(tgt, (ast.Subscript, ast.Attribute)):
            # store into a container/field: the container becomes at
            # least as tainted as the stored value
            if isinstance(tgt, ast.Subscript):
                self.expr(tgt.slice)
            base = tgt.value
            while isinstance(base, (ast.Attribute, ast.Subscript)):
                base = base.value
            if isinstance(base, ast.Name) and kind:
                cur = self.env.get(base.id, NONE)
                if kind > cur and base.id not in self.sanitized:
                    self.env[base.id] = kind
        elif isinstance(tgt, ast.Starred):
            self._assign_target(tgt.value, kind)

    # -- statements --

    def stmt(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(node, ast.Assign):
            kind = self.expr(node.value)
            for tgt in node.targets:
                self._assign_target(tgt, kind, node.value)
        elif isinstance(node, ast.AnnAssign):
            kind = self.expr(node.value) if node.value else NONE
            self._assign_target(node.target, kind, node.value)
            if isinstance(node.target, ast.Name) and _is_dict_annotation(
                node.annotation
            ):
                self.dict_names.add(node.target.id)
        elif isinstance(node, ast.AugAssign):
            kind = self.expr(node.value)
            if isinstance(node.target, ast.Name):
                cur = self._taint_of_name(node.target.id)
                self._assign_name(node.target.id, max(cur, kind))
            else:
                self._assign_target(node.target, kind)
        elif isinstance(node, ast.Expr):
            self.expr(node.value)
        elif isinstance(node, ast.Return):
            if node.value is not None:
                self.ret = max(self.ret, self.expr(node.value))
        elif isinstance(node, ast.If):
            self._branch(node.test, node.body, node.orelse)
        elif isinstance(node, (ast.While,)):
            self._sanitize_test(node.test)
            self.expr(node.test)
            self._loop_body(node.body)
            for s in node.orelse:
                self.stmt(s)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            self._for(node)
        elif isinstance(node, ast.Try):
            guards = _try_guards_index(node)
            if guards:
                self.index_guard += 1
            for s in node.body:
                self.stmt(s)
            if guards:
                self.index_guard -= 1
            for h in node.handlers:
                for s in h.body:
                    self.stmt(s)
            for s in node.orelse:
                self.stmt(s)
            for s in node.finalbody:
                self.stmt(s)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                self.expr(item.context_expr)
            for s in node.body:
                self.stmt(s)
        elif isinstance(node, ast.Assert):
            self._sanitize_test(node.test)
            self.expr(node.test)
            if node.msg is not None:
                self.expr(node.msg)
        elif isinstance(node, ast.Raise):
            if node.exc is not None:
                self.expr(node.exc)
        elif isinstance(node, (ast.Delete,)):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.env.pop(t.id, None)
                else:
                    self.expr(t)
        elif isinstance(node, (ast.Global, ast.Nonlocal, ast.Pass, ast.Break,
                               ast.Continue, ast.Import, ast.ImportFrom)):
            return
        else:
            # anything with an expression payload we didn't special-case
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self.expr(child)
                elif isinstance(child, ast.stmt):
                    self.stmt(child)

    def _branch(self, test, body, orelse) -> None:
        self.expr(test)
        self._sanitize_test(test)
        snap_env = dict(self.env)
        snap_san = set(self.sanitized)
        for s in body:
            self.stmt(s)
        env_b, san_b = self.env, self.sanitized
        self.env, self.sanitized = dict(snap_env), set(snap_san)
        for s in orelse:
            self.stmt(s)
        # join: taint survives if either branch leaves it tainted
        for name, kind in env_b.items():
            if kind > self.env.get(name, NONE):
                self.env[name] = kind
        self.sanitized &= san_b

    def _loop_body(self, body) -> None:
        # two joined passes so a name tainted late in the body is seen
        # by uses earlier in it on the next iteration
        for _ in range(2):
            for s in body:
                self.stmt(s)

    def _for(self, node) -> None:
        iter_kind = self.expr(node.iter)
        elem = _element_kind(node.iter, iter_kind, self)
        frame = amplify.LoopFrame(
            node,
            tainted=iter_kind != NONE,
            clamped=amplify.iter_clamped(node.iter),
        )
        if (
            self.rules & RULE_QUADRATIC
            and frame.tainted
            and not frame.clamped
        ):
            outer = amplify.enclosing_tainted(self.loops)
            if outer is not None:
                self.report_quadratic(node, outer)
        self.loops.append(frame)
        self._bind_loop_target(node.target, node.iter, elem)
        self._loop_body(node.body)
        self.loops.pop()
        for s in node.orelse:
            self.stmt(s)

    def _bind_loop_target(self, target, iter_node, elem: int) -> None:
        # `for i, x in enumerate(tainted)`: the index is bounded by the
        # collection's length (LEN), only the element carries its kind
        if (
            elem
            and isinstance(iter_node, ast.Call)
            and isinstance(iter_node.func, ast.Name)
            and iter_node.func.id == "enumerate"
            and isinstance(target, ast.Tuple)
            and len(target.elts) == 2
        ):
            self._assign_target(target.elts[0], LEN)
            self._assign_target(target.elts[1], elem)
            return
        self._assign_target(target, elem)

    def report_quadratic(self, node, outer) -> None:
        self.eng.report(
            "safe-quadratic-decode",
            self.key,
            node,
            "nested loop over attacker-sized collections (outer at "
            f"line {outer.node.lineno}) with no MAX_* clamp on either "
            "bound — one message buys O(n^2) work",
        )

    # -- sanitization --

    def _sanitize_test(self, test: ast.AST) -> None:
        """A comparison between a tainted name and any expression that
        is not itself VAL-tainted sanitizes that name for the rest of
        the function. `len(data)` is LEN even when `data` is attacker
        bytes — `if offset + n > len(data): raise` is THE canonical
        decoder guard and bounds n by bytes actually received."""
        for node in ast.walk(test):
            if not isinstance(node, ast.Compare):
                continue
            if any(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
            ):
                # identity tests (`data is None`) bound nothing — and
                # treating them as guards silently un-taints the whole
                # decoder (the tmtrace is-exemption lesson, again)
                continue
            if any(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
                # membership pins a value only against a FIXED universe
                # (`f in {1, 2}`, `k in ALLOWED`); `x in seen` against a
                # growing local accumulator bounds nothing — and it is
                # exactly the quadratic-scan shape the amplification
                # rule must keep seeing
                comp = node.comparators[0]
                fixed = isinstance(
                    comp, (ast.Set, ast.Tuple, ast.List, ast.Dict,
                           ast.Constant)
                ) or (
                    isinstance(comp, ast.Name)
                    and (
                        comp.id.isupper()
                        or comp.id in self.fixed_containers
                    )
                )
                if not fixed:
                    continue
            sides = [node.left] + list(node.comparators)
            names: Set[str] = set()
            has_bound_side = False
            for side in sides:
                # only VAL names need (or deserve) sanitizing: LEN
                # values are never flagged, and stripping their taint
                # would cut propagation into everything derived from
                # the payload
                side_names = {
                    n.id
                    for n in ast.walk(side)
                    if isinstance(n, ast.Name)
                    and self._taint_of_name(n.id) == VAL
                }
                names |= side_names
                if self.expr(side) != VAL:
                    has_bound_side = True
            if names and has_bound_side:
                self.sanitized |= names

    # -- expressions --

    def expr(self, node: Optional[ast.AST]) -> int:
        if node is None:
            return NONE
        if isinstance(node, ast.Name):
            return self._taint_of_name(node.id)
        if isinstance(node, ast.Constant):
            return NONE
        if isinstance(node, ast.Attribute):
            return self.expr(node.value)
        if isinstance(node, ast.Await):
            return self.expr(node.value)
        if isinstance(node, ast.Starred):
            return self.expr(node.value)
        if isinstance(node, ast.BinOp):
            left = self.expr(node.left)
            right = self.expr(node.right)
            if isinstance(node.op, ast.Mult) and self.rules & RULE_TAINT:
                self._check_repeat_sink(node, left, right)
            if (
                isinstance(node.op, ast.LShift)
                and right == VAL
                and self.rules & RULE_TAINT
            ):
                # `1 << size` materializes a size-bit Python bigint —
                # the allocation hides inside the shift operator
                self.eng.report(
                    "safe-alloc-unbounded",
                    self.key,
                    node,
                    "left shift by an unclamped attacker-controlled "
                    "integer — `1 << size` IS a size-bit allocation",
                )
            if isinstance(node.op, ast.Mod):
                # v % bound pins v into [0, bound)
                if left and not right:
                    return NONE
            return max(left, right)
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, ast.BoolOp):
            return max(self.expr(v) for v in node.values)
        if isinstance(node, ast.Compare):
            # inner comparisons still evaluate operands; membership
            # checks against tainted lists inside tainted loops are the
            # classic quadratic decode
            kinds = [self.expr(node.left)]
            kinds.extend(self.expr(c) for c in node.comparators)
            if (
                self.rules & RULE_QUADRATIC
                and len(node.ops) == 1
                and isinstance(node.ops[0], (ast.In, ast.NotIn))
            ):
                comp = node.comparators[0]
                if (
                    isinstance(comp, ast.Name)
                    and self._taint_of_name(comp.id)
                    and comp.id not in self.set_names
                    and comp.id not in self.dict_names
                ):
                    outer = amplify.enclosing_tainted(self.loops)
                    if outer is not None:
                        self.eng.report(
                            "safe-quadratic-decode",
                            self.key,
                            node,
                            f"membership scan of `{comp.id}` (a tainted "
                            "list, not a set) inside a loop over "
                            "attacker-sized input (outer at line "
                            f"{outer.node.lineno}) — O(n^2) duplicate "
                            "check",
                        )
                return NONE
            return NONE
        if isinstance(node, ast.IfExp):
            self.expr(node.test)
            self._sanitize_test(node.test)
            return max(self.expr(node.body), self.expr(node.orelse))
        if isinstance(node, ast.Subscript):
            return self._subscript(node)
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
            kinds = [self.expr(e) for e in node.elts]
            return max(kinds) if kinds else NONE
        if isinstance(node, ast.Dict):
            kinds = [self.expr(k) for k in node.keys if k is not None]
            kinds += [self.expr(v) for v in node.values]
            return max(kinds) if kinds else NONE
        if isinstance(
            node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
        ):
            return self._comprehension(node)
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                self.expr(v)
            return NONE
        if isinstance(node, ast.FormattedValue):
            self.expr(node.value)
            return NONE
        if isinstance(node, ast.Lambda):
            return NONE
        if isinstance(node, ast.Slice):
            self.expr(node.lower)
            self.expr(node.upper)
            self.expr(node.step)
            return NONE
        if isinstance(node, ast.NamedExpr):
            kind = self.expr(node.value)
            self._assign_target(node.target, kind)
            return kind
        # fallback: evaluate children
        kinds = [
            self.expr(c)
            for c in ast.iter_child_nodes(node)
            if isinstance(c, ast.expr)
        ]
        return max(kinds) if kinds else NONE

    def _subscript(self, node: ast.Subscript) -> int:
        base = self.expr(node.value)
        if isinstance(node.slice, ast.Slice):
            # slices clamp and the result is bounded by the source —
            # evaluate the bounds (for nested sinks) but no index sink
            self.expr(node.slice)
            return base
        idx_kind = self.expr(node.slice)
        if (
            self.rules & RULE_TAINT
            and idx_kind == VAL
            and self.index_guard == 0
            and isinstance(node.ctx, ast.Load)
            and not (
                isinstance(node.value, ast.Name)
                and node.value.id in self.dict_names
            )
        ):
            self.eng.report(
                "safe-index-unchecked",
                self.key,
                node,
                "subscript with an unclamped attacker-controlled "
                "integer — a signed wire field makes this silent "
                "negative-index aliasing, not just IndexError",
            )
        return base

    def _comprehension(self, node) -> int:
        result = NONE
        for gen in node.generators:
            iter_kind = self.expr(gen.iter)
            elem = _element_kind(gen.iter, iter_kind, self)
            frame = amplify.LoopFrame(
                gen.iter,
                tainted=iter_kind != NONE,
                clamped=amplify.iter_clamped(gen.iter),
            )
            if (
                self.rules & RULE_QUADRATIC
                and frame.tainted
                and not frame.clamped
            ):
                outer = amplify.enclosing_tainted(self.loops)
                if outer is not None:
                    self.report_quadratic(gen.iter, outer)
            self.loops.append(frame)
            self._bind_loop_target(gen.target, gen.iter, elem)
            for cond in gen.ifs:
                self.expr(cond)
                self._sanitize_test(cond)
        try:
            if isinstance(node, ast.DictComp):
                result = max(self.expr(node.key), self.expr(node.value))
            else:
                result = self.expr(node.elt)
        finally:
            for _ in node.generators:
                self.loops.pop()
        return result

    # -- calls --

    def _call(self, node: ast.Call) -> int:
        func = node.func
        # evaluate the receiver FIRST (never skip operand evaluation)
        recv_kind = NONE
        attr = ""
        if isinstance(func, ast.Attribute):
            recv_kind = self.expr(func.value)
            attr = func.attr
        arg_kinds = [self.expr(a) for a in node.args]
        kw_kinds = {}
        spread_kind = NONE  # a tainted `**kwargs` can land anywhere;
        for kw in node.keywords:  # it joins max_arg, never a position
            k = self.expr(kw.value)
            if kw.arg is not None:
                kw_kinds[kw.arg] = k
            else:
                spread_kind = max(spread_kind, k)
        max_arg = max(
            [NONE, spread_kind] + arg_kinds + list(kw_kinds.values())
        )

        name = ""
        if isinstance(func, ast.Name):
            name = func.id

        # mutating a container with tainted elements taints the
        # container (`seen.append(x)` — the list the membership scan
        # will walk); the two-pass loop body makes the later uses see it
        if (
            attr in ("append", "extend", "add", "insert", "appendleft",
                     "update", "setdefault")
            and max_arg
            and isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
        ):
            recv_name = func.value.id
            if recv_name not in self.sanitized:
                cur = self.env.get(recv_name, NONE)
                if max_arg > cur:
                    self.env[recv_name] = max_arg

        # builtins and parse primitives (checked BEFORE graph
        # resolution: decode_varint etc. are in-package, but their
        # semantics — LEN bytes in, VAL int out — are the model)
        if name == "len":
            return LEN if max_arg else NONE
        if name in ("int", "abs", "ord", "round"):
            return VAL if max_arg else NONE
        if name == "min" and arg_kinds:
            return min(arg_kinds)
        if name == "max" and arg_kinds:
            return max(arg_kinds)
        if name in _CLEAN_EXTERNALS:
            return NONE
        if name == "range":
            bound = max([NONE] + arg_kinds)
            if bound == VAL and self.rules & RULE_TAINT:
                self.eng.report(
                    "safe-alloc-unbounded",
                    self.key,
                    node,
                    "`range()` bound is an unclamped attacker-controlled "
                    "integer — ten wire bytes buy 2**63 iterations",
                )
            return bound
        if name in ("bytes", "bytearray") and arg_kinds:
            if arg_kinds[0] == VAL and self.rules & RULE_TAINT:
                self.eng.report(
                    "safe-alloc-unbounded",
                    self.key,
                    node,
                    f"`{name}()` sized by an unclamped attacker-"
                    "controlled integer — an over-allocation before any "
                    "validation runs",
                )
            return LEN if max_arg else NONE
        if name in _PARSE_VAL_FNS:
            return VAL if max_arg else NONE
        if name in _PARSE_LEN_FNS:
            return LEN if max_arg else NONE
        if name in ("set", "frozenset", "dict", "list", "tuple", "sorted",
                    "reversed", "enumerate", "zip", "sum"):
            return max_arg

        # attribute-call families
        if attr:
            if attr in _SOCKET_READ_ATTRS:
                if (
                    arg_kinds
                    and arg_kinds[0] == VAL
                    and self.rules & RULE_TAINT
                ):
                    # read(n)/readexactly(n) with a parsed, unclamped
                    # size: the buffer IS the allocation
                    self.eng.report(
                        "safe-alloc-unbounded",
                        self.key,
                        node,
                        f"`.{attr}()` sized by an unclamped attacker-"
                        "controlled integer — the receive buffer is "
                        "allocated before any bound is checked",
                    )
                return LEN
            if attr in _PARSE_VAL_FNS:
                return VAL if max(recv_kind, max_arg) else NONE
            if attr in _PARSE_LEN_FNS:
                return LEN if max(recv_kind, max_arg) else NONE
            if attr in ("unpack", "unpack_from", "from_bytes"):
                return VAL if max_arg else NONE
            if attr == "loads":
                return VAL if max_arg else NONE
            if recv_kind:
                if attr in _READER_INT or attr in _READER_VAL_COLLECTION:
                    return VAL
                if attr in _READER_LEN:
                    return max(recv_kind, LEN)

        # resolved in-package call
        site = self.sites.get((node.lineno, node.col_offset))
        if site is not None and site.target is not None:
            return self._internal_call(node, site, arg_kinds, kw_kinds,
                                       recv_kind, max_arg)
        if site is not None and site.external is not None:
            leaf = site.external.split(".")[-1]
            if leaf in _PARSE_VAL_FNS or leaf in ("loads", "unpack",
                                                  "unpack_from"):
                return VAL if max(recv_kind, max_arg) else NONE
            if leaf in _PARSE_LEN_FNS:
                return LEN if max(recv_kind, max_arg) else NONE
            if leaf in _CLEAN_EXTERNALS:
                return NONE
        # unknown/external: attacker data in, assume attacker data out —
        # EXCEPT through an opaque method on an untainted receiver (a
        # store/index lookup keyed by attacker input): the attacker
        # selects which of OUR values comes back, they don't inject an
        # unbounded integer, so VAL decays to LEN across the call
        result = max(recv_kind, max_arg)
        if attr and not recv_kind and result == VAL:
            result = LEN
        return result

    def _internal_call(
        self, node, site, arg_kinds, kw_kinds, recv_kind, max_arg
    ) -> int:
        target: FuncKey = site.target
        callee = self.eng.pkg.functions.get(target)
        if callee is None:
            return max_arg
        # map taints onto callee parameter names (keyword lookup covers
        # keyword-only params too — dropping them silently discarded
        # taint passed as `count=parsed_varint` into a kwonly arg)
        taints: Dict[str, int] = {}
        args = callee.node.args
        positional = [a.arg for a in args.posonlyargs + args.args]
        params = positional + [a.arg for a in args.kwonlyargs]
        pos = list(positional)
        if pos and pos[0] in ("self", "cls"):
            if recv_kind:
                taints[pos[0]] = recv_kind
            pos = pos[1:]
        for i, kind in enumerate(arg_kinds):
            if kind and i < len(pos):
                taints[pos[i]] = max(taints.get(pos[i], NONE), kind)
        for kname, kind in kw_kinds.items():
            if kind and kname in params:
                taints[kname] = max(taints.get(kname, NONE), kind)
        if not taints and not max(recv_kind, max_arg):
            return NONE
        if target == self.key:
            # recursion is a VAL-only sink: depth driven by a parsed
            # integer is unbounded; depth driven by nested structure
            # (LEN) costs the attacker bytes per level and is already
            # capped by the transport's message-size limit
            if (
                max_arg == VAL
                and self.rules & RULE_TAINT
                and self.index_guard == 0
            ):
                self.eng.report(
                    "safe-alloc-unbounded",
                    self.key,
                    node,
                    "recursion depth driven by an unclamped attacker-"
                    "controlled integer — the Python stack is the "
                    "allocation",
                )
            return max_arg
        ret = self.eng._flow_into(
            self.key, target, taints, self.rules, node.lineno
        )
        if target[1].endswith(".__init__"):
            # a constructor call evaluates to the INSTANCE, not to
            # __init__'s (None) return: the object wraps its tainted
            # arguments, so reader/message objects built over attacker
            # bytes stay tainted for the accessor special-cases
            return max(recv_kind, max_arg)
        return max(ret, NONE)

    def _check_repeat_sink(self, node, left, right) -> None:
        for seq_side, n_side, n_kind in (
            (node.left, node.right, right),
            (node.right, node.left, left),
        ):
            if n_kind != VAL:
                continue
            if isinstance(seq_side, ast.Constant) and isinstance(
                seq_side.value, (str, bytes)
            ):
                seq = True
            elif isinstance(seq_side, (ast.List, ast.Tuple)):
                seq = True
            else:
                seq = False
            if seq:
                self.eng.report(
                    "safe-alloc-unbounded",
                    self.key,
                    node,
                    "sequence repetition sized by an unclamped attacker-"
                    "controlled integer — an over-allocation before any "
                    "validation runs",
                )
                return


def _is_set_expr(node) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in ("set", "frozenset")
    return False


def _is_dict_expr(node) -> bool:
    if isinstance(node, (ast.Dict, ast.DictComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id == "dict"
    return False


def _is_dict_annotation(ann) -> bool:
    base = ann
    if isinstance(base, ast.Subscript):
        base = base.value
    name = ""
    if isinstance(base, ast.Name):
        name = base.id
    elif isinstance(base, ast.Attribute):
        name = base.attr
    return name in ("dict", "Dict", "Mapping", "MutableMapping",
                    "defaultdict", "OrderedDict", "Counter")


def _is_fixed_literal(node) -> bool:
    """Non-empty container literal whose members are all constants —
    a fixed membership/dispatch table, not an accumulator."""
    if isinstance(node, ast.Dict):
        return bool(node.keys) and all(
            isinstance(k, ast.Constant) for k in node.keys if k is not None
        )
    if isinstance(node, (ast.Set, ast.Tuple, ast.List)):
        return bool(node.elts) and all(
            isinstance(e, ast.Constant) for e in node.elts
        )
    return False


def _element_kind(iter_node, iter_kind: int, walker: _BodyWalker) -> int:
    """What iterating this expression binds: iter_fields and
    FieldReader.get_all yield parsed values (VAL); everything else
    yields elements no worse than the collection itself."""
    if iter_kind == NONE:
        return NONE
    if isinstance(iter_node, ast.Call):
        fn = iter_node.func
        leaf = ""
        if isinstance(fn, ast.Name):
            leaf = fn.id
        elif isinstance(fn, ast.Attribute):
            leaf = fn.attr
        if leaf in _PARSE_VAL_FNS or leaf in _READER_VAL_COLLECTION:
            return VAL
    return iter_kind


def _try_guards_index(node: ast.Try) -> bool:
    for h in node.handlers:
        if h.type is None:
            return True
        names: List[str] = []
        t = h.type
        elts = t.elts if isinstance(t, ast.Tuple) else [t]
        for e in elts:
            if isinstance(e, ast.Name):
                names.append(e.id)
            elif isinstance(e, ast.Attribute):
                names.append(e.attr)
        if any(n in _INDEX_GUARD_EXCS for n in names):
            return True
    return False
