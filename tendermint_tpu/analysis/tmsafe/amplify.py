"""Amplification helpers for the quadratic-decode rule.

A Byzantine peer's cheapest lever is not a forged signature — it is a
message shaped so that *pre-verification* work is superlinear in the
message's own size (arxiv 2302.00418 frames the multiplier: at 10k
validators, per-message decode cost is paid committee-many times).
The structural pattern is two nested iterations whose bounds BOTH come
from attacker-sized collections: duplicate scans, pairwise
intersection checks, per-part re-walks of the whole set.

`taintflow._BodyWalker` owns the traversal and taint facts; this
module owns the loop bookkeeping: the frame stack, and the clamp
recognition that keeps an explicitly bounded loop green:

- `for x in items[:MAX_...]` — clamped slice
- `for x in items[:16]` / any literal upper bound
- `range(min(n, MAX_...))` / `min(...)` anywhere in the iterable
- iterating a `MAX_*`-named object itself

One clamped bound is enough — n * MAX is linear in n.
"""

from __future__ import annotations

import ast
from typing import List, Optional

__all__ = ["LoopFrame", "iter_clamped", "enclosing_tainted"]

_CLAMP_NAME_MARKERS = ("MAX_", "_MAX", "LIMIT", "_CAP")


class LoopFrame:
    __slots__ = ("node", "tainted", "clamped")

    def __init__(self, node: ast.AST, tainted: bool, clamped: bool) -> None:
        self.node = node
        self.tainted = tainted
        self.clamped = clamped


def _is_clamp_name(node: ast.AST) -> bool:
    name = ""
    if isinstance(node, ast.Name):
        name = node.id
    elif isinstance(node, ast.Attribute):
        name = node.attr
    return name.isupper() and any(m in name for m in _CLAMP_NAME_MARKERS)


def iter_clamped(iter_node: ast.AST) -> bool:
    """True when the iterable carries an explicit upper clamp."""
    for node in ast.walk(iter_node):
        # items[:MAX] / items[:literal]
        if isinstance(node, ast.Slice) and node.upper is not None:
            up = node.upper
            if isinstance(up, ast.Constant) and isinstance(up.value, int):
                return True
            if _is_clamp_name(up):
                return True
        # min(n, MAX) — the clamp expression
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "min"
        ):
            return True
        if _is_clamp_name(node):
            return True
    return False


def enclosing_tainted(stack: List[LoopFrame]) -> Optional[LoopFrame]:
    """Innermost enclosing loop frame that is tainted and unclamped."""
    for frame in reversed(stack):
        if frame.tainted and not frame.clamped:
            return frame
    return None
