"""Validate-before-use ordering gate (`safe-unvalidated-use`).

The reference codebase's discipline is a convention: every reactor
handler calls `msg.validate_basic()` before letting the message touch
consensus state. Conventions rot; this pass makes the 25 in-tree
sites a checked catalog.

Model: a guarded breadth-first search over the PR-5 call graph.

- **Entries** — where attacker messages first meet domain logic:
  every function with an `Envelope`-annotated parameter (the p2p
  reactor handlers across consensus/blocksync/statesync/mempool/
  evidence/pex) and every `RPCRequest`-annotated route handler.
- **Sinks** — the consensus-mutation catalog (`MUTATION_SINKS`):
  VoteSet.add_vote, PartSet.add_part, the evidence pool's
  add_evidence, mempool check_tx, and the PeerState.apply_*/set_has_*
  family. Adding a new sink name here is a reviewed change.
- **Guard** — a call whose callee is `validate_basic` (resolved or
  syntactic `<recv>.validate_basic()` — receivers of decoded messages
  are dynamically typed, so the unresolved form counts too).

State at each function is a single bit: has SOME validate_basic call
already happened on this path? An outgoing edge at line L from
function F is guarded when F contains a validate_basic call at a line
before L (the universal `msg.validate_basic(); apply(msg)` shape), or
when F itself was entered validated. Reaching a sink unvalidated is a
finding, with the full entry -> ... -> sink witness chain.

Precision notes (documented, deliberate):
- The guard is not message-type-aware — any validate_basic before the
  sink-ward call counts. The codebase validates the envelope's own
  message at the top of each handler, so type confusion would require
  validating one message and applying another inside a single handler;
  the fuzzer half of tmsafe covers that corner dynamically.
- Queue hand-offs (send_peer_msg -> consumer loops) break the static
  call chain by design; the gate's contract is the HANDLER boundary:
  nothing may cross from an entry to a sink in one synchronous call
  chain unvalidated.
- Lexical before/after stands in for dominance. An `elif` arm's
  validate call cannot guard a different arm's sink in practice
  because every arm validates first — and removing any arm's validate
  WILL flip that arm's sink red, which is the regression the gate
  exists to catch.

Suppression: `# tmsafe: safe-unvalidated-use-ok — why` on (or in the
comment block above) the sink-calling line, for sinks whose validation
is definitionally elsewhere (an opaque tx has no validate_basic — the
app's CheckTx IS its validation).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..tmcheck.callgraph import FuncInfo, Package, _body_walk

__all__ = ["MUTATION_SINKS", "UnvalidatedUse", "check"]

FuncKey = Tuple[str, str]

# (path, qualname) -> why this is consensus mutation
MUTATION_SINKS: Dict[FuncKey, str] = {
    ("types/vote_set.py", "VoteSet.add_vote"): (
        "admits a vote into the tally that decides commits"
    ),
    ("types/part_set.py", "PartSet.add_part"): (
        "admits a block part into proposal assembly"
    ),
    ("evidence/pool.py", "EvidencePool.add_evidence"): (
        "admits evidence that can slash a validator"
    ),
    ("mempool/mempool.py", "TxMempool.check_tx"): (
        "admits a transaction into the mempool"
    ),
    ("mempool/mempool.py", "TxMempool.check_tx_batch"): (
        "admits a whole batch of transactions into the mempool (the "
        "sharded-admission fast path the gossip receive loop and the "
        "RPC coalescing batcher resolve to)"
    ),
    ("mempool/nop.py", "NopMempool.check_tx"): (
        "mempool admission (nop backend)"
    ),
    ("mempool/types.py", "Mempool.check_tx"): (
        "mempool admission (abstract protocol — what the RPC "
        "broadcast routes resolve to)"
    ),
    ("consensus/peer_state.py", "PeerState.apply_new_round_step"): (
        "rewrites our model of the peer's round state"
    ),
    ("consensus/peer_state.py", "PeerState.apply_new_valid_block"): (
        "rewrites our model of the peer's proposal block"
    ),
    ("consensus/peer_state.py", "PeerState.apply_proposal_pol"): (
        "rewrites the peer's proposal POL bits"
    ),
    ("consensus/peer_state.py", "PeerState.apply_has_vote"): (
        "marks votes as held by the peer (gossip suppression)"
    ),
    ("consensus/peer_state.py", "PeerState.apply_vote_set_bits"): (
        "rewrites the peer's vote bitmaps (gossip suppression)"
    ),
    ("consensus/peer_state.py", "PeerState.set_has_proposal"): (
        "marks the proposal as held by the peer"
    ),
    ("consensus/peer_state.py", "PeerState.set_has_proposal_block_part"): (
        "marks block parts as held by the peer"
    ),
    ("consensus/peer_state.py", "PeerState.set_has_vote"): (
        "marks a single vote as held by the peer"
    ),
}


class UnvalidatedUse:
    __slots__ = ("sink", "caller", "lineno", "col", "chain", "why")

    def __init__(self, sink, caller, lineno, col, chain, why):
        self.sink = sink  # FuncKey of the mutation sink
        self.caller = caller  # FuncKey of the function calling it
        self.lineno = lineno
        self.col = col
        self.chain = chain  # [entry, ..., caller] FuncKeys
        self.why = why


def _entry_keys(pkg: Package) -> List[FuncKey]:
    from .sources import _annotated_params

    out = []
    for key, fi in sorted(pkg.functions.items()):
        if _annotated_params(fi, "Envelope") or _annotated_params(
            fi, "RPCRequest"
        ):
            out.append(key)
        elif _has_envelope_loop(fi):
            out.append(key)
    return out


def _has_envelope_loop(fi: FuncInfo) -> bool:
    """The inline receive-loop shape: `async for envelope in
    <channel>` — the evidence/mempool reactors consume their channel
    directly instead of registering per-envelope handler methods, and
    those loops are entry points exactly like an Envelope-annotated
    handler."""
    for node in _body_walk(fi.node):
        if (
            isinstance(node, ast.AsyncFor)
            and isinstance(node.target, ast.Name)
            and node.target.id == "envelope"
        ):
            return True
    return False


def _validate_call_lines(fi: FuncInfo) -> List[int]:
    """Line numbers of `*.validate_basic(...)` calls in this body —
    syntactic, because decoded-message receivers rarely resolve."""
    out = []
    for node in _body_walk(fi.node):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "validate_basic"
        ):
            out.append(node.lineno)
    return sorted(out)


def check(
    pkg: Package, suppressed: Dict[str, Set[int]]
) -> Tuple[List[UnvalidatedUse], List[Tuple[str, int, FuncKey]]]:
    """`suppressed`: path -> line numbers carrying the
    safe-unvalidated-use-ok annotation (caller-side sink lines).
    Returns (findings, suppressed sink sites actually hit) — the
    second list feeds the head-catalog test that pins every accepted
    suppression to a finding it really covers."""
    entries = _entry_keys(pkg)
    validate_lines: Dict[FuncKey, List[int]] = {}

    def v_lines(key: FuncKey) -> List[int]:
        if key not in validate_lines:
            validate_lines[key] = _validate_call_lines(pkg.functions[key])
        return validate_lines[key]

    # BFS over (function, validated) states. parent links for witness.
    State = Tuple[FuncKey, bool]
    parent: Dict[State, Optional[State]] = {}
    queue: List[State] = []
    for e in entries:
        s = (e, False)
        if s not in parent:
            parent[s] = None
            queue.append(s)

    findings: Dict[Tuple[FuncKey, FuncKey, int], UnvalidatedUse] = {}
    hits: List[Tuple[str, int, FuncKey]] = []
    qi = 0
    while qi < len(queue):
        key, validated = queue[qi]
        qi += 1
        fi = pkg.functions[key]
        vlines = v_lines(key)
        for site in fi.calls:
            if site.target is None:
                continue
            guarded = validated or any(
                ln < site.lineno for ln in vlines
            )
            if site.target in MUTATION_SINKS:
                if guarded:
                    continue
                if site.lineno in suppressed.get(fi.path, ()):
                    hit = (fi.path, site.lineno, site.target)
                    if hit not in hits:
                        hits.append(hit)
                    continue
                fk = (site.target, key, site.lineno)
                if fk not in findings:
                    chain: List[FuncKey] = []
                    cur: Optional[State] = (key, validated)
                    while cur is not None:
                        chain.append(cur[0])
                        cur = parent[cur]
                    chain.reverse()
                    findings[fk] = UnvalidatedUse(
                        site.target,
                        key,
                        site.lineno,
                        site.col,
                        chain,
                        MUTATION_SINKS[site.target],
                    )
                continue
            if site.target not in pkg.functions:
                continue
            nxt = (site.target, guarded)
            if nxt not in parent:
                parent[nxt] = (key, validated)
                queue.append(nxt)
    return (
        sorted(
            findings.values(),
            key=lambda f: (f.caller[0], f.lineno, f.sink),
        ),
        hits,
    )
