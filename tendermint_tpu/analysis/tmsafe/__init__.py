"""tmsafe — whole-program adversarial-input safety proof.

Every gate before this one (tmlint/tmcheck/tmrace/tmtrace/tmlive,
PRs 4–9) proves properties of *our own* code. tmsafe proves properties
of our code **under attacker-chosen input**: a public p2p/RPC port is
hostile by definition, and the cheapest Byzantine attack is not a
forged signature but a message whose *decode-time* cost is asymmetric
— an over-allocation, a steered index, or superlinear work, all before
`validate_basic` (let alone a signature check) ever runs.

Four rules over the PR-5 call graph, sources machine-derived from the
same schema extraction whose output is pinned in tmcheck's golden
`schema.json` (see sources.py for the entry families):

- `safe-alloc-unbounded` (taintflow.py) — allocation or loop bound
  derived from an unbounded parsed integer (VAL taint) with no
  `MAX_*`/`len()` clamp between parse and use; includes tainted
  recursion depth.
- `safe-index-unchecked` (taintflow.py) — plain subscript with an
  unclamped parsed integer: signed wire fields make this silent
  negative-index aliasing.
- `safe-unvalidated-use` (validate.py) — a synchronous call chain
  from a p2p/RPC entry to a consensus-mutation sink (MUTATION_SINKS
  catalog) that does not pass a `validate_basic` call first.
- `safe-quadratic-decode` (amplify.py + taintflow.py) — nested
  iteration where BOTH bounds are attacker-sized, in decode/validate
  paths, with no clamp on either.

Suppressions: `# tmsafe: <rule>-ok — why` on the offending line or in
the comment block above it (comment_cover_lines, shared with the whole
family). Counted fingerprint baseline `safe_baseline.json` ships — and
is pinned by test — EMPTY.

Run via `scripts/lint.py --adv` (in the default full gate). The
dynamic twin is tests/test_decoder_fuzz.py: deterministic schema-
seeded mutations proving every decoder raises only sanctioned errors
within a byte budget. Static gate = no *reachable* unclamped sink;
fuzzer = no *observed* unclamped behavior; the division of labor is
documented in docs/static_analysis.md.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Set

from ..tmlint import (
    Violation,
    comment_cover_lines,
    load_baseline,
    new_violations,
    save_baseline,
)
from ..tmcheck.callgraph import Package, build_package
from . import amplify, sources, taintflow, validate  # noqa: F401
from .sources import derive_entries
from .taintflow import TaintEngine
from .validate import MUTATION_SINKS, check as validate_check

__all__ = [
    "RULES",
    "SAFE_BASELINE_PATH",
    "SAFE_BASELINE_NOTE",
    "SafeReport",
    "analyze",
    "safe_violations",
    "new_safe_violations",
    "update_safe_baseline",
    "suppressed_lines",
]

SAFE_BASELINE_PATH = os.path.join(
    os.path.dirname(__file__), "safe_baseline.json"
)

SAFE_BASELINE_NOTE = (
    "Accepted pre-existing adversarial-input findings, fingerprinted by "
    "rule:path:sha1(source_line)[:12]. New findings are anything over "
    "these counts. Do not hand-edit counts to sneak a finding in — fix "
    "it, or suppress it in-file with a justified "
    "'# tmsafe: <rule>-ok — why'."
)

RULES = [
    (
        "safe-alloc-unbounded",
        "allocation or loop bound derived from an unbounded parsed "
        "integer with no MAX_*/len() clamp between parse and use",
    ),
    (
        "safe-index-unchecked",
        "plain subscript indexed by an unclamped parsed integer "
        "(signed wire fields alias negatively, silently)",
    ),
    (
        "safe-unvalidated-use",
        "synchronous path from a p2p/RPC entry to a consensus-mutation "
        "sink with no validate_basic call before the sink",
    ),
    (
        "safe-quadratic-decode",
        "nested iteration with both bounds attacker-sized in "
        "decode/validate paths and no MAX_* clamp on either",
    ),
]

_SUPPRESS_RE = re.compile(
    r"#\s*tmsafe:\s*(safe-[a-z\-]+)-ok\b"
)


def suppressed_lines(lines: List[str]) -> Dict[str, Set[int]]:
    """rule -> covered line numbers for `# tmsafe: <rule>-ok — why`
    annotations (same comment-block-above convention as the family)."""
    out: Dict[str, Set[int]] = {}
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rule = m.group(1)
        out.setdefault(rule, set()).update(
            comment_cover_lines(lines, i, text)
        )
    return out


class SafeReport:
    def __init__(self) -> None:
        self.entries: List[sources.Entry] = []
        self.taint_findings: List[taintflow.Finding] = []
        self.unvalidated: List[validate.UnvalidatedUse] = []
        self.violations: List[Violation] = []
        self.stats: Dict[str, int] = {}
        # (rule, path, line) of findings dropped by an in-file
        # suppression — the head-catalog test pins this set
        self.suppressed: List[tuple] = []


def analyze(pkg: Optional[Package] = None) -> SafeReport:
    pkg = pkg or build_package()
    report = SafeReport()

    supp: Dict[str, Dict[str, Set[int]]] = {}
    for path, mod in pkg.modules.items():
        m = suppressed_lines(mod.lines)
        if m:
            supp[path] = m

    def is_suppressed(rule: str, path: str, lineno: int) -> bool:
        return lineno in supp.get(path, {}).get(rule, ())

    def line_text(path: str, lineno: int) -> str:
        lines = pkg.modules[path].lines
        if 1 <= lineno <= len(lines):
            return lines[lineno - 1].strip()
        return ""

    violations: List[Violation] = []

    # -- taint + amplification --
    report.entries = derive_entries(pkg)
    engine = TaintEngine(pkg, report.entries)
    findings = engine.run()
    report.taint_findings = findings
    n_supp = 0
    for f in findings:
        if is_suppressed(f.rule, f.path, f.lineno):
            n_supp += 1
            report.suppressed.append((f.rule, f.path, f.lineno))
            continue
        chain = engine.chain(f.key)
        witness = " -> ".join(chain)
        violations.append(
            Violation(
                rule=f.rule,
                path=f.path,
                line=f.lineno,
                col=f.col,
                message=f"{f.detail}; witness: {witness}",
                source=line_text(f.path, f.lineno),
            )
        )

    # -- validate-before-use --
    unval_supp = {
        path: m.get("safe-unvalidated-use", set())
        for path, m in supp.items()
    }
    uses, unval_hits = validate_check(pkg, unval_supp)
    report.unvalidated = uses
    for path, lineno, _sink in unval_hits:
        n_supp += 1
        report.suppressed.append(("safe-unvalidated-use", path, lineno))
    for u in uses:
        sink_fi = pkg.functions[u.sink]
        chain = " -> ".join(
            pkg.functions[k].render() for k in u.chain
        )
        violations.append(
            Violation(
                rule="safe-unvalidated-use",
                path=u.caller[0],
                line=u.lineno,
                col=u.col,
                message=(
                    f"reaches {sink_fi.render()} ({u.why}) with no "
                    f"validate_basic on the path {chain} -> "
                    f"{sink_fi.qualname}"
                ),
                source=line_text(u.caller[0], u.lineno),
            )
        )

    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    report.violations = violations
    per_rule: Dict[str, int] = {rid: 0 for rid, _ in RULES}
    for v in violations:
        per_rule[v.rule] = per_rule.get(v.rule, 0) + 1
    report.stats = {
        "entries": len(report.entries),
        "region": sum(
            1 for st in engine.states.values() if st.analyzed
        ),
        "suppressed": n_supp,
        "sinks_cataloged": len(MUTATION_SINKS),
        **{f"findings[{rid}]": n for rid, n in per_rule.items()},
    }
    return report


def safe_violations(pkg: Optional[Package] = None) -> List[Violation]:
    return analyze(pkg).violations


def new_safe_violations(
    pkg: Optional[Package] = None,
    baseline_path: Optional[str] = None,
) -> List[Violation]:
    violations = safe_violations(pkg)
    baseline = load_baseline(baseline_path or SAFE_BASELINE_PATH)
    return new_violations(violations, baseline)


def update_safe_baseline(
    pkg: Optional[Package] = None,
    baseline_path: Optional[str] = None,
) -> Dict[str, int]:
    return save_baseline(
        safe_violations(pkg),
        baseline_path or SAFE_BASELINE_PATH,
        note=SAFE_BASELINE_NOTE,
    )
