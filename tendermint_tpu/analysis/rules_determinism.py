"""Determinism rules: consensus-critical byte streams must be
replica-identical.

Every rule here protects the same invariant: the bytes a validator
signs (`types/canonical.py` sign-bytes), the hashes it computes
(`crypto/merkle.py`, `crypto/tmhash.py`, header/commit hashes in
`types/`), and the proto encodings it gossips (`encoding/proto.py`)
must come out byte-identical on every replica, every run, every
platform — or replicas sign conflicting byte streams and the chain
forks or halts (SURVEY.md "Determinism & safety"; the EdDSA-in-
committee-consensus batching literature assumes the same property).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from .tmlint import (
    Module,
    Rule,
    Violation,
    dotted_name,
    is_consensus_critical,
    is_replay_scope,
    register,
)

# wall-clock reads: each replica gets a different answer, so any use
# in a hash/sign-bytes input diverges replicas instantly
_WALLCLOCK = {
    "time.time",
    "time.time_ns",
    "datetime.now",
    "datetime.utcnow",
    "datetime.today",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.datetime.today",
    "datetime.date.today",
    "date.today",
}

# the global (unseeded / OS-entropy) randomness surface
_RANDOM_MODULE_FNS = {
    "random",
    "choice",
    "choices",
    "shuffle",
    "sample",
    "randint",
    "randrange",
    "getrandbits",
    "uniform",
    "betavariate",
    "gauss",
    "normalvariate",
    "expovariate",
    "triangular",
    "randbytes",
}
_ENTROPY_CALLS = {
    "os.urandom",
    "uuid.uuid1",
    "uuid.uuid4",
    "secrets.token_bytes",
    "secrets.token_hex",
    "secrets.randbits",
    "secrets.choice",
}


def _resolved_call_name(mod: Module, node: ast.Call) -> str:
    """The call target as a dotted module path, resolving from-imports:
    `time.time()` and `from time import time as now; now()` both
    resolve to 'time.time' — the lint gate must not be evadable by
    import style."""
    name = dotted_name(node.func)
    if name and "." not in name:
        orig = mod.from_import_orig.get(name)
        if orig is not None:
            return f"{orig[0]}.{orig[1]}"
    return name


@register
class DetWallclock(Rule):
    id = "det-wallclock"
    title = "wall-clock read in a consensus-critical module"
    rationale = (
        "time.time()/datetime.now() differ across replicas; a "
        "wall-clock value flowing into sign-bytes or a hash forks the "
        "chain. Protocol-required timestamps (BFT time) must come in "
        "through the one blessed entry point (types/timestamp.now_ns) "
        "or a suppressed, justified site."
    )

    def applies(self, mod: Module) -> bool:
        return is_consensus_critical(mod.path)

    def check(self, mod: Module) -> Iterator[Violation]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _resolved_call_name(mod, node)
            if name in _WALLCLOCK:
                yield self.violation(
                    mod,
                    node,
                    f"wall-clock read `{name}()` in a consensus-critical "
                    "module; replicas will disagree — plumb the value in "
                    "from the caller or use the blessed timestamp entry "
                    "point",
                )


@register
class DetRandom(Rule):
    id = "det-random"
    title = "unseeded/global randomness in replay-critical code"
    rationale = (
        "The module-global `random.*` functions and OS entropy "
        "(os.urandom, uuid4, secrets) are unseeded: consensus-critical "
        "uses fork replicas, and uses anywhere in the message-driven "
        "state machines (consensus/, blocksync/, statesync/) break "
        "seed-exact schedulefuzz replay. Use an injected "
        "`random.Random(seed)` — gossip picks go through "
        "libs/rng.py's seedable instance."
    )

    def applies(self, mod: Module) -> bool:
        return is_replay_scope(mod.path)

    def check(self, mod: Module) -> Iterator[Violation]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _resolved_call_name(mod, node)
            if not name:
                continue
            if name in _ENTROPY_CALLS:
                yield self.violation(
                    mod,
                    node,
                    f"OS-entropy call `{name}()` in replay-critical code; "
                    "not reproducible from a seed",
                )
                continue
            parts = name.split(".")
            # `random.choice(...)` / `_random.shuffle(...)` — the
            # module-global unseeded RNG under its conventional import
            # names. Instance calls (`rng.choice`, `self.rng.choice`,
            # `GOSSIP.choice`) are the approved pattern and don't match.
            if (
                len(parts) == 2
                and parts[0] in ("random", "_random")
                and parts[1] in _RANDOM_MODULE_FNS
            ):
                yield self.violation(
                    mod,
                    node,
                    f"unseeded global RNG call `{name}()`; route through "
                    "an injectable seeded random.Random (libs/rng.py) so "
                    "fuzz failures replay from their seed",
                )


@register
class DetFloat(Rule):
    id = "det-float"
    title = "float arithmetic in a consensus-critical module"
    rationale = (
        "IEEE-754 results vary with evaluation order, compiler, and "
        "platform; a float flowing into sign-bytes/hash/encode input "
        "is nondeterministic across the fleet. Consensus math is "
        "integer math (nanoseconds, not fractional seconds)."
    )

    def applies(self, mod: Module) -> bool:
        return is_consensus_critical(mod.path)

    def check(self, mod: Module) -> Iterator[Violation]:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Constant) and isinstance(
                node.value, float
            ):
                yield self.violation(
                    mod,
                    node,
                    f"float literal `{node.value!r}` in a "
                    "consensus-critical module",
                )
            elif isinstance(node, ast.BinOp) and isinstance(
                node.op, ast.Div
            ):
                yield self.violation(
                    mod,
                    node,
                    "true division `/` produces a float; use `//` "
                    "integer division in consensus-critical code",
                )
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name == "float":
                    yield self.violation(
                        mod,
                        node,
                        "float() conversion in a consensus-critical module",
                    )


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        return dotted_name(node.func) in ("set", "frozenset")
    return False


@register
class DetSetIter(Rule):
    id = "det-set-iter"
    title = "unordered set iteration in a consensus-critical module"
    rationale = (
        "CPython set iteration order depends on element hashes — for "
        "str/bytes keys that's randomized per process "
        "(PYTHONHASHSEED), so two replicas walking the same set feed "
        "their hash/sign-bytes/encode functions different byte "
        "orders. Iterate `sorted(s)` or keep an ordered structure "
        "(dicts preserve insertion order and are fine)."
    )

    def applies(self, mod: Module) -> bool:
        return is_consensus_critical(mod.path)

    def check(self, mod: Module) -> Iterator[Violation]:
        # names bound to set expressions, per enclosing function (or
        # module scope for top-level code)
        set_names: dict = {}  # scope node -> set of names
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and _is_set_expr(node.value):
                scope = mod.enclosing_function(node) or mod.tree
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        set_names.setdefault(scope, set()).add(tgt.id)

        def iter_is_set(it: ast.AST, at: ast.AST) -> bool:
            if _is_set_expr(it):
                return True
            if isinstance(it, ast.Name):
                scope = mod.enclosing_function(at) or mod.tree
                return it.id in set_names.get(scope, ())
            return False

        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.For, ast.AsyncFor)):
                if iter_is_set(node.iter, node):
                    yield self.violation(
                        mod,
                        node,
                        "iterating a set in a consensus-critical module; "
                        "order is hash-dependent — iterate sorted(...) "
                        "instead",
                    )
            elif isinstance(
                node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
            ):
                for gen in node.generators:
                    if iter_is_set(gen.iter, node):
                        yield self.violation(
                            mod,
                            node,
                            "comprehension over a set in a "
                            "consensus-critical module; order is "
                            "hash-dependent — iterate sorted(...) instead",
                        )
