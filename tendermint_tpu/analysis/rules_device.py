"""Device-hygiene rules for the JAX hot path.

The throughput story (PERF.md) depends on two properties of the
dispatch path: the host never *implicitly* blocks on the device (the
gather is the one deliberate sync point, guarded by a deadline
watchdog), and program shapes stay inside the padded bucket set so
XLA never recompiles mid-round. Both properties die silently — an
`.item()` in a loop or a Python-int shape argument works fine and
just makes the hot path 100x slower — so they're lint rules, not
review notes.
"""

from __future__ import annotations

import ast
from typing import Iterator

from .tmlint import Module, Rule, Violation, dotted_name, is_device_scope, register

_NP_TRANSFER = {
    "np.asarray",
    "np.array",
    "numpy.asarray",
    "numpy.array",
    "onp.asarray",
    "onp.array",
}

_JNP_SHAPED_CTORS = {
    "jnp.zeros",
    "jnp.ones",
    "jnp.full",
    "jnp.empty",
    "jnp.arange",
    "jax.numpy.zeros",
    "jax.numpy.ones",
    "jax.numpy.full",
    "jax.numpy.empty",
    "jax.numpy.arange",
}


def _is_static_shape(node: ast.AST) -> bool:
    """Shape arguments that cannot leak a per-call Python scalar:
    constants, tuples/lists of constants, attribute reads (self.BUCKET,
    cls.SIZE) and SCREAMING_CASE names — configuration, not data."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_static_shape(e) for e in node.elts)
    if isinstance(node, ast.Attribute):
        return True
    if isinstance(node, ast.Name):
        return node.id == node.id.upper()
    if isinstance(node, ast.UnaryOp):
        return _is_static_shape(node.operand)
    return False


@register
class DevHostSync(Rule):
    id = "dev-host-sync"
    title = "implicit device→host sync on the JAX hot path"
    rationale = (
        "`.item()`, `float(device_val)`, and np.asarray/np.array on a "
        "device array each block the host until the device catches "
        "up, serializing the async dispatch pipeline that overlaps "
        "host assembly with device compute. The gather is the ONE "
        "deliberate sync point (deadline-guarded); any other sync is "
        "either a bug or needs a suppression naming why it's "
        "host-side data."
    )

    def applies(self, mod: Module) -> bool:
        return is_device_scope(mod.path)

    def check(self, mod: Module) -> Iterator[Violation]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "item"
                and not node.args
                and not node.keywords
            ):
                yield self.violation(
                    mod,
                    node,
                    "`.item()` forces a blocking device→host transfer; "
                    "gather whole arrays at the deliberate sync point "
                    "instead",
                )
            elif name == "float" and node.args and not isinstance(
                node.args[0], ast.Constant
            ):
                yield self.violation(
                    mod,
                    node,
                    "float(x) on a non-literal blocks if x is a device "
                    "value; keep scalars on device or convert at the "
                    "gather",
                )
            elif name in _NP_TRANSFER:
                yield self.violation(
                    mod,
                    node,
                    f"`{name}(...)` copies through host memory and "
                    "synchronizes if handed a device array; use jnp ops "
                    "or move the conversion to the gather",
                )


@register
class DevShapeLeak(Rule):
    id = "dev-shape-leak"
    title = "dynamic Python shape argument forces XLA recompiles"
    rationale = (
        "jnp.zeros(n)/arange(n) with a per-call Python int compiles "
        "one XLA program per distinct n — a mid-round recompile costs "
        "more than the whole batch saves. Shapes must come from the "
        "padded bucket configuration (constants / class attributes), "
        "never from data-dependent scalars like len(batch)."
    )

    def applies(self, mod: Module) -> bool:
        return is_device_scope(mod.path)

    def check(self, mod: Module) -> Iterator[Violation]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name not in _JNP_SHAPED_CTORS:
                continue
            if not node.args:
                continue
            shape = node.args[0]
            if _is_static_shape(shape):
                continue
            yield self.violation(
                mod,
                node,
                f"`{name}` called with a dynamic shape argument "
                f"(`{ast.unparse(shape)}`); every distinct value "
                "compiles a new XLA program — pad to a configured "
                "bucket size instead",
            )
