"""Device-hygiene node engines for the JAX hot path.

The throughput story (PERF.md) depends on two properties of the
dispatch path: the host never *implicitly* blocks on the device (the
gather is the one deliberate sync point, guarded by a deadline
watchdog), and program shapes stay inside the padded bucket set so
XLA never recompiles mid-round. Both properties die silently — an
`.item()` in a loop or a Python-int shape argument works fine and
just makes the hot path 100x slower — so they're machine checks, not
review notes.

Since PR 8 these rules are NOT registered with tmlint: tmtrace's
whole-program pass (analysis/tmtrace/shapeflow.py) owns them — same
rule ids, same `# tmlint: disable=` suppressions honored, but
evaluated interprocedurally over the widened device scope (ops/
included, bucket-provenance dataflow for shapes, ARRAY taint for the
traced region) so one site is never reported by two tools. The
DevHostSync class stays here as the shared node-level engine
(shapeflow evaluates it over the legacy dispatch scope); the old
DevShapeLeak node check is fully superseded by shapeflow's
bucket-provenance dataflow and was removed.
"""

from __future__ import annotations

import ast
from typing import Iterator

from .tmlint import Module, Rule, Violation, dotted_name, is_device_scope

_NP_TRANSFER = {
    "np.asarray",
    "np.array",
    "numpy.asarray",
    "numpy.array",
    "onp.asarray",
    "onp.array",
}

_JNP_SHAPED_CTORS = {
    "jnp.zeros",
    "jnp.ones",
    "jnp.full",
    "jnp.empty",
    "jnp.arange",
    "jax.numpy.zeros",
    "jax.numpy.ones",
    "jax.numpy.full",
    "jax.numpy.empty",
    "jax.numpy.arange",
}


class DevHostSync(Rule):
    id = "dev-host-sync"
    title = "implicit device→host sync on the JAX hot path"
    rationale = (
        "`.item()`, `float(device_val)`, and np.asarray/np.array on a "
        "device array each block the host until the device catches "
        "up, serializing the async dispatch pipeline that overlaps "
        "host assembly with device compute. The gather is the ONE "
        "deliberate sync point (deadline-guarded); any other sync is "
        "either a bug or needs a suppression naming why it's "
        "host-side data."
    )

    def applies(self, mod: Module) -> bool:
        return is_device_scope(mod.path)

    def check(self, mod: Module) -> Iterator[Violation]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "item"
                and not node.args
                and not node.keywords
            ):
                yield self.violation(
                    mod,
                    node,
                    "`.item()` forces a blocking device→host transfer; "
                    "gather whole arrays at the deliberate sync point "
                    "instead",
                )
            elif name == "float" and node.args and not isinstance(
                node.args[0], ast.Constant
            ):
                yield self.violation(
                    mod,
                    node,
                    "float(x) on a non-literal blocks if x is a device "
                    "value; keep scalars on device or convert at the "
                    "gather",
                )
            elif name in _NP_TRANSFER:
                yield self.violation(
                    mod,
                    node,
                    f"`{name}(...)` copies through host memory and "
                    "synchronizes if handed a device array; use jnp ops "
                    "or move the conversion to the gather",
                )
