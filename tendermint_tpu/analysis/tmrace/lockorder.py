"""Static lock-order graph, diffed against lockwatch's RANK table.

lockwatch (PR 4) witnesses held->acquiring edges on the paths the test
suite happens to execute; this pass derives them along EVERY static
path from every thread root, so:

- rank acyclicity is proven over paths no test executes: a static
  edge between two RANKED locks must go low -> high, and the full
  static graph (ranked or not) must be acyclic — a witnessed A->B in
  one function plus B->A in another is a latent deadlock even if no
  test interleaves them;
- the RANK table can never silently drift from the code: every edge
  lockwatch documents in `RANK_EDGES` as "static" must actually be
  derivable from the source, and edges only observable at runtime
  (through dynamic dispatch the call graph cannot resolve) must say
  so with "runtime-only". Deleting the code that creates a static
  edge without updating the table fails the gate.

Static lock identities map onto lockwatch's rank names through
`STATIC_RANK_NAMES` below — the same class-not-instance naming both
systems use. A same-name edge (lock class nested inside itself) on a
non-reentrant lock is reported as a cycle: lockwatch treats witnessed
self-loops as instance-order hazards, and statically they are either
a self-deadlock (same instance) or an unordered instance pair.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..lockwatch import RANK, RANK_EDGES, _find_cycles
from .lockset import LockEdge

__all__ = [
    "STATIC_RANK_NAMES",
    "rank_violations",
    "cycles",
    "rank_drift",
]

# static lock identity -> lockwatch RANK name. The left side is the
# `<path>:<name>` / `<path>:<Class>.<attr>` identity lockset.py
# assigns; keep this in lockstep with lockwatch.enable()'s
# instrument_attr/namer calls (test_tmrace pins the round trip).
STATIC_RANK_NAMES: Dict[str, str] = {
    "crypto/breaker.py:_REG_LOCK": "breaker.registry",
    "crypto/breaker.py:CircuitBreaker._lock": "breaker.instance",
    "crypto/sigcache.py:_lock": "sigcache.rotate",
    "crypto/tpu_verifier.py:_wedged_lock": "tpu_verifier.wedged",
    "libs/trace.py:_ring_lock": "trace.ring",
    "libs/metrics.py:_Metric._lock": "metrics.metric",
    "libs/metrics.py:Registry._lock": "metrics.registry",
}


def ranked_edges(
    edges: Dict[Tuple[str, str], LockEdge],
    names: Optional[Dict[str, str]] = None,
) -> Dict[Tuple[str, str], LockEdge]:
    """The statically derived edges translated into RANK-name space
    (edges with an unranked endpoint are dropped)."""
    names = STATIC_RANK_NAMES if names is None else names
    out: Dict[Tuple[str, str], LockEdge] = {}
    for (a, b), e in edges.items():
        na, nb = names.get(a), names.get(b)
        if na is not None and nb is not None:
            out.setdefault((na, nb), e)
    return out


def rank_violations(
    edges: Dict[Tuple[str, str], LockEdge],
    rank: Optional[Dict[str, int]] = None,
    names: Optional[Dict[str, str]] = None,
) -> List[dict]:
    """Static edges contradicting the declared order: a ranked lock
    held while acquiring a lower-ranked one."""
    rank = RANK if rank is None else rank
    out: List[dict] = []
    for (na, nb), e in sorted(ranked_edges(edges, names).items()):
        ra, rb = rank.get(na), rank.get(nb)
        if ra is not None and rb is not None and ra > rb:
            out.append(
                {
                    "edge": (na, nb),
                    "rank": (ra, rb),
                    "where": e.where,
                    "func": e.func,
                }
            )
    return out


def cycles(edges: Dict[Tuple[str, str], LockEdge]) -> List[List[str]]:
    """Simple cycles (self-loops included) in the full static graph —
    the same detector as lockwatch's witnessed-order graph, so the
    static and runtime gates can never diverge on what counts as a
    cycle."""
    return _find_cycles(set(edges))


def rank_drift(
    edges: Dict[Tuple[str, str], LockEdge],
    rank_edges: Optional[Dict[Tuple[str, str], str]] = None,
    names: Optional[Dict[str, str]] = None,
) -> List[dict]:
    """RANK_EDGES entries declared "static" that the source no longer
    produces — the table drifted from the code. "runtime-only" entries
    are exempt by declaration; anything else in the classification
    column is itself an error."""
    rank_edges = RANK_EDGES if rank_edges is None else rank_edges
    derived = ranked_edges(edges, names)
    out: List[dict] = []
    for (a, b), cls in sorted(rank_edges.items()):
        if cls == "runtime-only":
            continue
        if cls != "static":
            out.append(
                {
                    "edge": (a, b),
                    "reason": f"unknown RANK_EDGES class {cls!r} "
                    "(use 'static' or 'runtime-only')",
                }
            )
            continue
        if (a, b) not in derived:
            out.append(
                {
                    "edge": (a, b),
                    "reason": (
                        "declared static in lockwatch.RANK_EDGES but not "
                        "derivable from any call path — the code moved; "
                        "update the table (or mark the edge runtime-only "
                        "with a reason)"
                    ),
                }
            )
    return out
