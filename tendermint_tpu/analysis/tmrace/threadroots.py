"""Static thread-root discovery — who runs concurrently with whom.

`go test -race` sees every goroutine the suite actually spawns; a
static analysis has to *enumerate* the concurrent entry points
instead. This module finds them all over tmcheck's call graph:

- **Spawned roots** — the target of every `threading.Thread(...)` /
  `threading.Timer(...)` construction and every
  `loop.run_in_executor(...)` submission in the package: the breaker's
  probe thread and retry timer, the gather-watchdog daemon, the cmd
  reader, etc. Each distinct target function is one *identity*, and a
  spawned identity is self-concurrent (nothing statically bounds how
  many instances run at once — two watchdogs race each other just as
  well as a watchdog races the main loop).
- **The main loop** — every `async def` in the package. All coroutines
  run on the process's single asyncio event-loop thread (the consensus
  receive loop, every RPC/WS handler, the reactors), so they share ONE
  identity, `main-loop`, which is NOT self-concurrent: two handlers
  interleave only at awaits, never preempt mid-bytecode. RPC handler
  registration tables (string-keyed dict literals of bound methods,
  rpc/core.py `routes()`) and the consensus receive loop are detected
  and labeled in the catalog, but they fold into the same identity.
- **Test-harness spawns** — `threading.Thread(target=...)` sites in
  the repo's tests/ tree (the chaos/hammer suites). The target's body
  is scanned for calls into the package through its imports; each
  spawn site is its own self-concurrent identity, because the hammer
  tests exist precisely to drive package functions from many threads.

A function reachable (through the call graph) from two different
identities — or from one self-concurrent identity — executes
concurrently with itself or others: that set is the *concurrent
region* the lockset analysis checks. Unresolvable spawn targets
(lambdas, functools.partial) produce no root: like tmcheck's edges,
roots are deliberately under-approximate and the docs say so.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from ..tmlint import dotted_name as _dotted
from ..tmcheck.callgraph import FuncInfo, ModuleIndex, Package, _body_walk

__all__ = [
    "MAIN_IDENTITY",
    "ThreadRoot",
    "discover_roots",
    "discover_test_roots",
    "reach",
]

MAIN_IDENTITY = "main-loop"

# a spawned identity reaching this many functions is normal; identity
# count is small, so per-identity BFS stays cheap
FuncKey = Tuple[str, str]


class ThreadRoot:
    """One concurrent entry point.

    `identity` groups roots that run on the same thread (every async
    def shares `main-loop`); `self_concurrent` marks identities whose
    code races *itself* (spawned threads/timers, test hammers)."""

    __slots__ = ("key", "kind", "identity", "self_concurrent", "where")

    def __init__(
        self,
        key: FuncKey,
        kind: str,
        identity: str,
        self_concurrent: bool,
        where: str,
    ) -> None:
        self.key = key
        self.kind = kind
        self.identity = identity
        self.self_concurrent = self_concurrent
        self.where = where

    def render(self) -> str:
        flag = " [self-concurrent]" if self.self_concurrent else ""
        return f"{self.kind:12s} {self.key[0]}:{self.key[1]}{flag} ({self.where})"


# ---------------------------------------------------------------------------
# spawn-site detection


def _is_threading_name(mod: ModuleIndex, func: ast.AST, names) -> bool:
    d = _dotted(func)
    if d in {f"threading.{n}" for n in names}:
        return True
    if isinstance(func, ast.Name) and func.id in names:
        fi = mod.from_imports.get(func.id)
        return fi is not None and fi[1] == "threading"
    return False


def spawn_target(mod: ModuleIndex, call: ast.Call):
    """(kind, target_expr) for a concurrency-spawning call, else
    (None, None). Thread takes `target=`, Timer its second positional
    (or `function=`), run_in_executor its second positional."""
    if _is_threading_name(mod, call.func, ("Thread",)):
        for kw in call.keywords:
            if kw.arg == "target":
                return "thread", kw.value
        return "thread", None  # Thread subclass-less, no target: noop
    if _is_threading_name(mod, call.func, ("Timer",)):
        if len(call.args) >= 2:
            return "timer", call.args[1]
        for kw in call.keywords:
            if kw.arg == "function":
                return "timer", kw.value
        return "timer", None
    d = _dotted(call.func)
    if d.endswith(".run_in_executor") and len(call.args) >= 2:
        return "executor", call.args[1]
    return None, None


def _resolve_ref(
    pkg: Package,
    mod: ModuleIndex,
    fi: FuncInfo,
    expr: ast.AST,
    local_types: Dict[str, str],
) -> Optional[FuncKey]:
    """Resolve a *function reference* (not a call) — `self._loop`,
    `_reader`, `mod.fn` — to an in-package function key."""
    if isinstance(expr, ast.Name):
        name = expr.id
        # nested def in the enclosing function
        nested = (fi.path, f"{fi.qualname}.{name}")
        if nested in pkg.functions:
            return nested
        if name in mod.functions:
            return (mod.path, name)
        entry = mod.from_imports.get(name)
        if entry is not None and entry[0] is not None:
            target = pkg.module_for_dotted(entry[0])
            if target is not None and entry[2] in target.functions:
                return (target.path, entry[2])
        return None
    if isinstance(expr, ast.Attribute):
        dotted = _dotted(expr)
        if not dotted:
            return None
        parts = dotted.split(".")
        head, attr = parts[0], parts[-1]
        if head in ("self", "cls") and len(parts) == 2 and fi.class_name:
            return pkg._method_key(mod, fi.class_name, attr)
        if len(parts) == 2 and head in local_types:
            return pkg._method_key(mod, local_types[head], attr)
        if len(parts) == 2 and head in mod.var_class:
            owner, cname = mod.var_class[head]
            return pkg._method_key(owner, cname, attr)
        # module attr through an import
        entry = mod.from_imports.get(head)
        if entry is not None and entry[0] is not None and len(parts) == 2:
            base = entry[0] + "." + entry[2] if entry[0] else entry[2]
            target = pkg.module_for_dotted(base)
            if target is not None and attr in target.functions:
                return (target.path, attr)
        alias = mod.import_alias.get(head)
        if alias is not None:
            prefix = pkg.pkg_name + "."
            if alias.startswith(prefix):
                target = pkg.module_for_dotted(alias[len(prefix):])
                if target is not None and attr in target.functions:
                    return (target.path, attr)
    return None


def discover_roots(pkg: Package) -> List[ThreadRoot]:
    """Every in-package concurrent entry point; see module docstring
    for the catalog semantics."""
    roots: Dict[Tuple[FuncKey, str], ThreadRoot] = {}

    def add(key, kind, identity, self_conc, where):
        cur = roots.get((key, identity))
        if cur is None:
            roots[(key, identity)] = ThreadRoot(
                key, kind, identity, self_conc, where
            )

    for fi in pkg.functions.values():
        mod = pkg.modules[fi.path]
        # main-loop identity: every coroutine runs on the event loop
        if isinstance(fi.node, ast.AsyncFunctionDef):
            kind = "async"
            if "receive" in fi.qualname.split(".")[-1] and fi.path.startswith(
                "consensus/"
            ):
                kind = "receive-loop"
            add(
                fi.key, kind, MAIN_IDENTITY, False,
                f"{fi.path}:{fi.lineno}",
            )
        local_types = pkg._local_types(mod, fi.node)
        for node in _body_walk(fi.node):
            # spawned threads / timers / executor jobs
            if isinstance(node, ast.Call):
                kind, target = spawn_target(mod, node)
                if kind is not None and target is not None:
                    key = _resolve_ref(pkg, mod, fi, target, local_types)
                    if key is not None:
                        add(
                            key, kind,
                            f"{kind}:{key[0]}:{key[1]}", True,
                            f"{fi.path}:{node.lineno}",
                        )
            # RPC/WS registration tables: a string-keyed dict literal
            # of bound methods (rpc/core.py routes()); handlers are
            # coroutines on the event loop — catalog them explicitly
            elif isinstance(node, ast.Dict) and len(node.keys) >= 3:
                if not all(
                    isinstance(k, ast.Constant) and isinstance(k.value, str)
                    for k in node.keys
                    if k is not None
                ):
                    continue
                for v in node.values:
                    key = _resolve_ref(pkg, mod, fi, v, local_types)
                    if key is not None:
                        add(
                            key, "rpc", MAIN_IDENTITY, False,
                            f"{fi.path}:{node.lineno}",
                        )
    return sorted(
        roots.values(), key=lambda r: (r.identity, r.key)
    )


# ---------------------------------------------------------------------------
# callback escape: function refs that run on someone else's thread


def _param_names(fn_node: ast.AST, is_method: bool) -> List[str]:
    args = fn_node.args
    names = [a.arg for a in list(args.posonlyargs) + list(args.args)]
    if is_method and names and names[0] in ("self", "cls"):
        names = names[1:]
    return names


def callback_roots(
    pkg: Package, roots: List["ThreadRoot"]
) -> List["ThreadRoot"]:
    """Function references that escape into a *dynamic-call sink*
    executing under a spawned identity — the breaker-probe idiom:
    `b.set_probe(fn)` stores `fn` on the instance, and the probe
    thread later calls `self._probe_fn()`. Statically: find parameters
    whose value is (a) called directly inside an identity-reachable
    function, or (b) stored into a `self.<attr>` that such a function
    calls; then every function reference (or `lambda: f(...)` body
    call) passed for that parameter anywhere in the package becomes a
    root under that identity. Iterated to fixpoint by analyze()."""
    identities, _ = reach(pkg, roots)
    self_conc = {r.identity for r in roots if r.self_concurrent}
    existing = {(r.key, r.identity) for r in roots}

    # (path, class, attr) -> [(method key, param name)] for
    # `self.<attr> = <param>` assignments
    attr_params: Dict[Tuple[str, str, str], List[Tuple[FuncKey, str]]] = {}
    for fi in pkg.functions.values():
        if not fi.class_name:
            continue
        params = set(_param_names(fi.node, True))
        for node in _body_walk(fi.node):
            if not isinstance(node, ast.Assign):
                continue
            if not (
                isinstance(node.value, ast.Name)
                and node.value.id in params
            ):
                continue
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    attr_params.setdefault(
                        (fi.path, fi.class_name, t.attr), []
                    ).append((fi.key, node.value.id))

    # sinks: (function key, param name) -> identities the value runs on
    sinks: Dict[Tuple[FuncKey, str], Set[str]] = {}
    for key, ids in identities.items():
        fi = pkg.functions[key]
        params = set(_param_names(fi.node, bool(fi.class_name)))
        for node in _body_walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name) and f.id in params:
                sinks.setdefault((key, f.id), set()).update(ids)
            elif (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "self"
                and fi.class_name
            ):
                for mkey, pname in attr_params.get(
                    (fi.path, fi.class_name, f.attr), ()
                ):
                    sinks.setdefault((mkey, pname), set()).update(ids)
    if not sinks:
        return []
    sink_funcs = {k for k, _ in sinks}

    out: List[ThreadRoot] = []

    def add_root(key: FuncKey, ids: Set[str], where: str) -> None:
        for identity in ids:
            if (key, identity) in existing:
                continue
            existing.add((key, identity))
            out.append(
                ThreadRoot(
                    key,
                    "callback",
                    identity,
                    identity in self_conc,
                    where,
                )
            )

    for fi in pkg.functions.values():
        mod = pkg.modules[fi.path]
        local_types = pkg._local_types(mod, fi.node)
        site_by_pos = {(c.lineno, c.col): c for c in fi.calls}
        for node in _body_walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            site = site_by_pos.get((node.lineno, node.col_offset))
            if site is None or site.target not in sink_funcs:
                continue
            target_fi = pkg.functions[site.target]
            pnames = _param_names(target_fi.node, bool(target_fi.class_name))
            # map positional and keyword args onto parameter names
            bound: List[Tuple[str, ast.AST]] = []
            for pos, arg in enumerate(node.args):
                if pos < len(pnames):
                    bound.append((pnames[pos], arg))
            for kw in node.keywords:
                if kw.arg:
                    bound.append((kw.arg, kw.value))
            for pname, arg in bound:
                ids = sinks.get((site.target, pname))
                if not ids:
                    continue
                where = f"{fi.path}:{node.lineno}"
                if isinstance(arg, ast.Lambda):
                    for sub in ast.walk(arg.body):
                        if isinstance(sub, ast.Call):
                            s2 = site_by_pos.get(
                                (sub.lineno, sub.col_offset)
                            )
                            if s2 is not None and s2.target is not None:
                                add_root(s2.target, ids, where)
                else:
                    key = _resolve_ref(pkg, mod, fi, arg, local_types)
                    if key is not None:
                        add_root(key, ids, where)
    return out


# ---------------------------------------------------------------------------
# test-harness spawns (tests/ is outside the package root)


def discover_test_roots(
    pkg: Package, tests_root: Optional[str] = None
) -> List[ThreadRoot]:
    """Thread spawns in the repo's tests/ tree whose targets call into
    the package: each spawn site is its own self-concurrent identity
    (the hammer/chaos suites drive package functions from N threads).
    Resolution is import-map based — `from tendermint_tpu.crypto
    import sigcache` then `sigcache.seen_key(...)` inside the spawned
    function body. Unresolvable targets are skipped (documented
    under-approximation)."""
    if tests_root is None:
        # package root layout: <repo>/tendermint_tpu — tests live at
        # <repo>/tests
        tests_root = os.path.join(os.path.dirname(pkg.root), "tests")
    if not os.path.isdir(tests_root):
        return []
    out: List[ThreadRoot] = []
    for name in sorted(os.listdir(tests_root)):
        if not name.endswith(".py"):
            continue
        path = os.path.join(tests_root, name)
        try:
            with open(path, "r", encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
        except (SyntaxError, OSError):
            continue
        out.extend(_test_file_roots(pkg, name, tree))
    return out


def _test_file_roots(
    pkg: Package, filename: str, tree: ast.Module
) -> List[ThreadRoot]:
    pkg_prefix = pkg.pkg_name + "."
    # local name -> internal dotted module ("" = package root)
    mod_alias: Dict[str, str] = {}
    # local name -> (module path, function name)
    fn_alias: Dict[str, FuncKey] = {}
    # local name -> (module path, class name)
    cls_alias: Dict[str, Tuple[str, str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.startswith(pkg_prefix) or a.name == pkg.pkg_name:
                    local = a.asname or a.name.split(".")[0]
                    inner = (
                        a.name[len(pkg_prefix):]
                        if a.name.startswith(pkg_prefix)
                        else ""
                    )
                    mod_alias[local] = inner
        elif isinstance(node, ast.ImportFrom) and node.module:
            m = node.module
            if not (m == pkg.pkg_name or m.startswith(pkg_prefix)):
                continue
            inner = m[len(pkg_prefix):] if m.startswith(pkg_prefix) else ""
            for a in node.names:
                local = a.asname or a.name
                sub = inner + "." + a.name if inner else a.name
                target = pkg.module_for_dotted(sub)
                if target is not None:
                    mod_alias[local] = sub
                    continue
                owner = pkg.module_for_dotted(inner)
                if owner is None:
                    continue
                if a.name in owner.functions:
                    fn_alias[local] = (owner.path, a.name)
                elif a.name in owner.classes:
                    cls_alias[local] = (owner.path, a.name)

    # local defs by name (nested defs included: hammers live inside
    # test functions)
    defs: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node

    def pkg_calls(fn_node: ast.AST) -> Set[FuncKey]:
        found: Set[FuncKey] = set()
        # local `x = Cls(...)` over imported package classes
        local_cls: Dict[str, Tuple[str, str]] = {}
        for n in ast.walk(fn_node):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                d = _dotted(n.value.func)
                cname = d.split(".")[-1]
                resolved = cls_alias.get(cname)
                if resolved is None and "." in d:
                    # `watch = lockwatch.LockWatch()` through a module
                    # import
                    head = d.split(".")[0]
                    if head in mod_alias:
                        owner = pkg.module_for_dotted(mod_alias[head])
                        if owner is not None and cname in owner.classes:
                            resolved = (owner.path, cname)
                if resolved is not None:
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            local_cls[t.id] = resolved
        for n in ast.walk(fn_node):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            if isinstance(f, ast.Name):
                if f.id in fn_alias:
                    found.add(fn_alias[f.id])
            elif isinstance(f, ast.Attribute) and isinstance(
                f.value, ast.Name
            ):
                head = f.value.id
                if head in mod_alias:
                    owner = pkg.module_for_dotted(mod_alias[head])
                    if owner is not None and f.attr in owner.functions:
                        found.add((owner.path, f.attr))
                elif head in local_cls:
                    mpath, cname = local_cls[head]
                    owner = pkg.modules.get(mpath)
                    if owner is not None:
                        key = pkg._method_key(owner, cname, f.attr)
                        if key is not None:
                            found.add(key)
        return found

    out: List[ThreadRoot] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        target = None
        if d in ("threading.Thread", "Thread"):
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
        elif d in ("threading.Timer", "Timer") and len(node.args) >= 2:
            target = node.args[1]
        if target is None:
            continue
        identity = f"test-spawn:{filename}:{node.lineno}"
        reached: Set[FuncKey] = set()
        if isinstance(target, ast.Name) and target.id in defs:
            reached = pkg_calls(defs[target.id])
        elif isinstance(target, ast.Name) and target.id in fn_alias:
            reached = {fn_alias[target.id]}
        elif isinstance(target, ast.Attribute):
            # obj.method where obj = Cls(...) locally in the file
            pass  # handled through pkg_calls of enclosing defs only
        for key in sorted(reached):
            out.append(
                ThreadRoot(
                    key, "test-spawn", identity, True,
                    f"tests/{filename}:{node.lineno}",
                )
            )
    return out


# ---------------------------------------------------------------------------
# reachability


def reach(
    pkg: Package, roots: List[ThreadRoot]
) -> Tuple[Dict[FuncKey, Set[str]], Dict[str, Dict[FuncKey, Optional[FuncKey]]]]:
    """(identities, parents): per-function set of root identities that
    reach it, plus per-identity BFS parent maps for witness chains
    (shortest path from a root, exactly like tmcheck's taint pass)."""
    by_identity: Dict[str, List[FuncKey]] = {}
    for r in roots:
        if r.key in pkg.functions:
            by_identity.setdefault(r.identity, []).append(r.key)
    identities: Dict[FuncKey, Set[str]] = {}
    parents: Dict[str, Dict[FuncKey, Optional[FuncKey]]] = {}
    for identity, keys in by_identity.items():
        parent: Dict[FuncKey, Optional[FuncKey]] = {}
        queue = []
        for k in keys:
            if k not in parent:
                parent[k] = None
                queue.append(k)
        i = 0
        while i < len(queue):
            key = queue[i]
            i += 1
            identities.setdefault(key, set()).add(identity)
            for site in pkg.functions[key].calls:
                t = site.target
                if t is not None and t in pkg.functions and t not in parent:
                    parent[t] = key
                    queue.append(t)
        parents[identity] = parent
    return identities, parents


def witness_chain(
    pkg: Package,
    parents: Dict[str, Dict[FuncKey, Optional[FuncKey]]],
    identity: str,
    key: FuncKey,
) -> List[str]:
    """Rendered shortest call chain root -> ... -> key for one
    identity."""
    chain: List[str] = []
    cur: Optional[FuncKey] = key
    pmap = parents.get(identity, {})
    while cur is not None:
        fi = pkg.functions[cur]
        chain.append(f"{fi.path}:{fi.qualname}")
        cur = pmap.get(cur)
    chain.reverse()
    return chain
