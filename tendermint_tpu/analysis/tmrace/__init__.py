"""tmrace — whole-program static data-race and lock-order analysis.

The Go reference leans on `go test -race` (dynamic happens-before) and
the lockrank build tag; lockwatch (PR 4) replicates the runtime half
but only witnesses what the suite executes. tmrace is the static half,
on the same substrate tmcheck's taint pass uses (the PR-5 call graph):

1. **Thread roots** (`threadroots.py`): every concurrent entry point —
   `threading.Thread`/`Timer`/`run_in_executor` targets, the asyncio
   main loop (all coroutines: consensus receive loop, RPC/WS
   handlers), and the tests/ hammer spawns — and the *concurrent
   region*: functions reachable from ≥2 root identities (or one
   self-concurrent one).
2. **Lockset dataflow** (`lockset.py`): MUST-held locksets propagated
   along every call path (recognizing `with <lock>:`, the `*_locked`
   convention, and tmlint's justified exemptions); writes to module
   globals and shared instance fields whose write-lockset intersection
   is empty are flagged. Per-site `# tmrace: race-ok` /
   `# tmrace: guarded-by=<lock>` suppressions and a counted
   fingerprint baseline (`race_baseline.json`) in the tmlint/tmcheck
   style.
3. **Static lock order** (`lockorder.py`): held->acquiring edges along
   all static paths, checked for cycles and diffed against lockwatch's
   RANK table and its `RANK_EDGES` classification — rank acyclicity is
   proven over paths no test executes, and the table cannot silently
   drift from the code.

Run via `scripts/lint.py --race` (or the default full gate); tier-1
gates live in tests/test_tmrace.py. docs/static_analysis.md documents
the root catalog, the lockset rules, the suppression/baseline policy,
and the static-vs-lockwatch division of labor.
"""

from __future__ import annotations

import os
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ..tmlint import (
    Violation,
    load_baseline,
    new_violations,
    save_baseline,
)
from ..tmcheck.callgraph import Package, build_package
from . import lockorder, lockset, threadroots
from .lockorder import rank_drift, rank_violations
from .lockset import WILDCARD, FuncSummary, Summarizer, propagate
from .threadroots import (
    ThreadRoot,
    discover_roots,
    discover_test_roots,
    reach,
    witness_chain,
)

__all__ = [
    "RULES",
    "RACE_BASELINE_PATH",
    "RACE_BASELINE_NOTE",
    "RaceReport",
    "analyze",
    "race_violations",
    "new_race_violations",
    "update_race_baseline",
]

FuncKey = Tuple[str, str]

RACE_BASELINE_PATH = os.path.join(
    os.path.dirname(__file__), "race_baseline.json"
)

# written into race_baseline.json so the artifact's own instructions
# name tmrace's suppression forms, not tmlint's
RACE_BASELINE_NOTE = (
    "Accepted pre-existing race findings, fingerprinted by "
    "rule:path:sha1(source_line)[:12]. New findings are anything over "
    "these counts. Do not hand-edit counts to sneak a new finding in "
    "— fix it, or suppress it with a justified '# tmrace: race-ok — "
    "why' / '# tmrace: guarded-by=<lock>' (or, for lock-discipline "
    "sites, a justified '# tmlint: disable=lock-global-mutation')."
)

# the tmrace rule catalog (mirrored by --list-rules and the docs table)
RULES = [
    (
        "race-unguarded-global",
        "module global written from the concurrent region with an "
        "empty write-lockset intersection",
    ),
    (
        "race-unguarded-field",
        "shared instance field written from the concurrent region "
        "with an empty write-lockset intersection",
    ),
    (
        "race-lock-order",
        "static held->acquiring edge contradicting lockwatch RANK, or "
        "a cycle in the static lock graph",
    ),
    (
        "race-rank-drift",
        "lockwatch RANK_EDGES entry declared static but no longer "
        "derivable from source",
    ),
]


class RaceReport:
    """Everything one analysis run produced (the CLI and the tests
    read different slices)."""

    def __init__(self) -> None:
        self.roots: List[ThreadRoot] = []
        self.identities: Dict[FuncKey, Set[str]] = {}
        self.self_concurrent: Set[str] = set()
        self.concurrent_region: Set[FuncKey] = set()
        self.edges: Dict[Tuple[str, str], lockset.LockEdge] = {}
        self.truncated_contexts = 0
        self.violations: List[Violation] = []


def _effective_degree(ids: Set[str], self_conc: Set[str]) -> int:
    return len(ids) + (1 if any(i in self_conc for i in ids) else 0)


def analyze(
    pkg: Optional[Package] = None,
    tests_root: Optional[str] = None,
    rank: Optional[Dict[str, int]] = None,
    rank_edges: Optional[Dict[Tuple[str, str], str]] = None,
    rank_names: Optional[Dict[str, str]] = None,
    include_test_roots: bool = True,
) -> RaceReport:
    pkg = pkg or build_package()
    report = RaceReport()

    # -- roots and the concurrent region --
    roots = discover_roots(pkg)
    if include_test_roots:
        roots += discover_test_roots(pkg, tests_root)
    # callback escape (the breaker set_probe idiom) can expose new
    # sink-reaching functions, which can expose new callbacks: iterate
    # to fixpoint (bounded: each round adds ≥1 root from a finite set)
    while True:
        extra = threadroots.callback_roots(pkg, roots)
        if not extra:
            break
        roots += extra
    report.roots = roots
    report.self_concurrent = {
        r.identity for r in roots if r.self_concurrent
    }
    identities, parents = reach(pkg, roots)
    report.identities = identities
    report.concurrent_region = {
        k
        for k, ids in identities.items()
        if _effective_degree(ids, report.self_concurrent) >= 2
    }

    # -- summaries + lockset propagation --
    summarizer = Summarizer(pkg)
    summaries: Dict[FuncKey, FuncSummary] = {}
    for key in identities:
        summaries[key] = summarizer.summarize_function(pkg.functions[key])
    root_keys = sorted({r.key for r in roots})
    entry_contexts, edges, truncated = propagate(pkg, summaries, root_keys)
    report.edges = edges
    report.truncated_contexts = truncated

    known_locks: Set[str] = set()
    for a, b in edges:
        known_locks.update((a, b))
    for s in summaries.values():
        for w in s.with_sites:
            known_locks.add(w.lock)
        known_locks |= set(s.convention)

    # -- suppression maps --
    race_ok: Dict[str, Set[int]] = {}
    guarded_by: Dict[str, Dict[int, Set[str]]] = {}
    for path, mod in pkg.modules.items():
        ok, gb = lockset.suppression_maps(mod.lines)
        race_ok[path] = ok
        guarded_by[path] = {
            ln: {
                lockset.resolve_guard_name(a, known_locks)
                for a in asserted
            }
            for ln, asserted in gb.items()
        }

    # -- collect shared-state accesses --
    class _Site:
        __slots__ = ("key", "lineno", "write", "locks", "what")

        def __init__(self, key, lineno, write, locks, what):
            self.key = key
            self.lineno = lineno
            self.write = write
            self.locks = locks
            self.what = what

    # collect from EVERY rooted function, not just the concurrent
    # region: a race pairs sites across identities, and each endpoint
    # may itself be reachable from only ONE root (main-loop-only write
    # vs probe-thread-only write) — the per-variable degree cut below,
    # over the union of the sites' identities, is the concurrency
    # filter. Iterating `identities` (insertion-ordered dict) rather
    # than the region set also keeps site order hash-seed-independent.
    by_var: Dict[tuple, List[_Site]] = {}
    for key in identities:
        summary = summaries[key]
        ctxs = entry_contexts.get(key)
        must_entry: FrozenSet[str] = (
            frozenset.intersection(*ctxs) if ctxs else frozenset()
        )
        base = must_entry | summary.convention
        path = key[0]
        for acc in summary.accesses:
            if acc.lineno in race_ok.get(path, ()):
                continue
            locks = acc.locks | base | frozenset(
                guarded_by.get(path, {}).get(acc.lineno, ())
            )
            by_var.setdefault(acc.var, []).append(
                _Site(key, acc.lineno, acc.write, locks, acc.what)
            )

    violations: List[Violation] = []

    def _line_text(path: str, lineno: int) -> str:
        lines = pkg.modules[path].lines
        if 1 <= lineno <= len(lines):
            return lines[lineno - 1].strip()
        return ""

    for var, sites in sorted(by_var.items(), key=lambda kv: str(kv[0])):
        ids: Set[str] = set()
        for s in sites:
            ids |= identities.get(s.key, set())
        if _effective_degree(ids, report.self_concurrent) < 2:
            continue
        writes = [s for s in sites if s.write]
        # a write under a wildcard lock is audited-guarded: skip it
        real_writes = [w for w in writes if WILDCARD not in w.locks]
        if not real_writes:
            continue
        candidate = frozenset.intersection(
            *[w.locks for w in real_writes]
        )
        if candidate:
            continue
        if var[0] == "g":
            rule = "race-unguarded-global"
            what = f"module global `{var[2]}`"
        else:
            rule = "race-unguarded-field"
            what = f"shared field `{var[2]}.{var[3]}`"
        id_list = ", ".join(sorted(ids)[:4]) + (
            f" (+{len(ids) - 4} more)" if len(ids) > 4 else ""
        )
        others = "; ".join(
            f"{w.key[0]}:{w.lineno} holds "
            f"{{{', '.join(sorted(w.locks)) or ''}}}"
            for w in real_writes[:4]
        )
        for w in real_writes:
            if w.locks:
                # guarded by SOMETHING, just inconsistently: still a
                # finding, but anchor the message on the inconsistency
                detail = (
                    f"write locksets never intersect "
                    f"(this site holds {{{', '.join(sorted(w.locks))}}})"
                )
            else:
                detail = "written with no lock held on any path"
            chains = []
            for ident in sorted(identities.get(w.key, set()))[:2]:
                chains.append(
                    " -> ".join(
                        witness_chain(pkg, parents, ident, w.key)
                    )
                )
            violations.append(
                Violation(
                    rule=rule,
                    path=w.key[0],
                    line=w.lineno,
                    col=0,
                    message=(
                        f"{what} {w.what}: {detail}; concurrent roots: "
                        f"{id_list}; write sites: {others}; witness: "
                        + " | ".join(chains)
                    ),
                    source=_line_text(w.key[0], w.lineno),
                )
            )

    # -- lock order --
    for v in rank_violations(edges, rank=rank, names=rank_names):
        path, _, line = v["where"].partition(":")
        a, b = v["edge"]
        violations.append(
            Violation(
                rule="race-lock-order",
                path=path,
                line=int(line or 1),
                col=0,
                message=(
                    f"static lock-order edge {a} (rank {v['rank'][0]}) "
                    f"held while acquiring {b} (rank {v['rank'][1]}) "
                    f"in {v['func']} contradicts lockwatch RANK"
                ),
                source=_line_text(path, int(line or 1)),
            )
        )
    for cyc in lockorder.cycles(edges):
        # every consecutive pair in a reported cycle (including the
        # canonical rotation's first pair) is an edge of the input
        first = edges[(cyc[0], cyc[1 % len(cyc)])]
        path, _, line = first.where.partition(":")
        violations.append(
            Violation(
                rule="race-lock-order",
                path=path,
                line=int(line or 1),
                col=0,
                message=(
                    "static lock-order cycle "
                    + " -> ".join(cyc + [cyc[0]])
                    + f" (first edge in {first.func}) — latent deadlock "
                    "even if no test interleaves it"
                ),
                source=_line_text(path, int(line or 1)),
            )
        )
    drift_path = "analysis/lockwatch.py"
    drift_line = _find_rank_edges_line(pkg, drift_path)
    for d in rank_drift(edges, rank_edges=rank_edges, names=rank_names):
        a, b = d["edge"]
        violations.append(
            Violation(
                rule="race-rank-drift",
                path=drift_path,
                line=drift_line,
                col=0,
                message=f"RANK_EDGES ({a} -> {b}): {d['reason']}",
                source=f"RANK_EDGES[({a!r}, {b!r})]",
            )
        )

    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    report.violations = violations
    return report


def _find_rank_edges_line(pkg: Package, path: str) -> int:
    mod = pkg.modules.get(path)
    if mod is None:
        return 1
    for i, text in enumerate(mod.lines, start=1):
        if text.startswith("RANK_EDGES"):
            return i
    return 1


def race_violations(
    pkg: Optional[Package] = None, **kwargs
) -> List[Violation]:
    return analyze(pkg, **kwargs).violations


def new_race_violations(
    pkg: Optional[Package] = None,
    baseline_path: Optional[str] = None,
    **kwargs,
) -> List[Violation]:
    """Race findings beyond the checked-in baseline (same counted
    fingerprint semantics as tmlint/tmcheck)."""
    violations = race_violations(pkg, **kwargs)
    baseline = load_baseline(baseline_path or RACE_BASELINE_PATH)
    return new_violations(violations, baseline)


def update_race_baseline(
    pkg: Optional[Package] = None,
    baseline_path: Optional[str] = None,
    **kwargs,
) -> Dict[str, int]:
    return save_baseline(
        race_violations(pkg, **kwargs),
        baseline_path or RACE_BASELINE_PATH,
        note=RACE_BASELINE_NOTE,
    )
