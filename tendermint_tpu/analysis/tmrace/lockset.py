"""Interprocedural lockset dataflow — the static half of `-race`.

RacerD-style over-approximation sized for this codebase: for every
function in the *concurrent region* (reachable from ≥2 thread roots,
or from one self-concurrent root — see `threadroots`), compute the set
of locks MUST-held at each shared-state access, and flag writes whose
lockset intersection across all write sites is empty.

**Lock identity is the lock CLASS, not the instance** (exactly how
lockwatch and Go's lockrank name locks): `self._lock` inside any
`CircuitBreaker` method is `crypto/breaker.py:CircuitBreaker._lock`,
attributed to the class (or base class) whose `__init__` creates it,
so `Counter.inc`'s `with self._lock:` names the shared
`libs/metrics.py:_Metric._lock` class. Module-level locks are
`<path>:<name>`. The same attribution applies to the shared state
itself: instance fields are `(path, Class, attr)`, module globals
`(path, name)`.

What counts as holding a lock at a site:

- an enclosing `with <lock>:` in the same function (a context whose
  dotted expression names a lock born from `threading.Lock/RLock/
  Condition`, or whose name contains "lock" — tmlint's heuristic);
- the function's MUST-entry lockset: the *intersection* of locks held
  at every call path from every thread root (computed by the
  context-sensitive traversal shared with the lock-order pass);
- the `*_locked` naming convention (tmlint's exemption): a method
  `foo_locked` of class C is by contract called with C's `_lock`
  held; a module-level `*_locked` function is treated as guarded by
  an unknowable caller lock (wildcard);
- a `# tmrace: guarded-by=<lock>` annotation on the line (an audited
  claim the dataflow cannot see, e.g. a lock acquired through an
  indirection).

Exemptions, in the established suppression style:

- `# tmrace: race-ok — why` on the line (or the comment block above):
  the access is intentionally unsynchronized and the comment says why;
- `# tmlint: disable=lock-global-mutation` sites: those carry a
  justified GIL-atomicity argument already (sigcache's set ops, the
  trace ring append) — one audited claim should not need two tags;
- writes inside `__init__`/`__new__` (single-threaded construction);
- import-time (module body) statements.

Known over/under-approximations (documented in
docs/static_analysis.md): lock-free READS of lock-guarded state are
not flagged (the codebase's deliberate GIL fast-path idiom, same call
as tmlint's mutation-only rule); per-instance locks collapsing onto
the class identity means a global guarded by *different instances'*
locks would falsely pass; unresolved call edges hide whatever runs
behind them.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ..tmlint import dotted_name as _dotted
from ..tmcheck.callgraph import FuncInfo, ModuleIndex, Package

__all__ = [
    "Access",
    "FuncSummary",
    "LockEdge",
    "WILDCARD",
    "summarize",
    "propagate",
    "born_locks",
]

FuncKey = Tuple[str, str]

# a lock the analysis cannot name: holding it satisfies guardedness
# (under-approximate on findings, never a false positive), but it
# contributes no lock-order edges
WILDCARD = "?"

# one entry lockset context per (function, held-set) pair; beyond the
# cap further contexts are dropped (the report counts them)
MAX_CONTEXTS = 16

_MUTATING_METHODS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "add", "discard", "update", "setdefault", "appendleft", "popleft",
    "sort", "reverse",
}

_LOCK_CTORS = {"Lock", "RLock", "Condition"}

_RACE_OK_RE = re.compile(r"#\s*tmrace:\s*race-ok\b")
_GUARDED_BY_RE = re.compile(r"#\s*tmrace:\s*guarded-by=([A-Za-z0-9_.\-]+)")
_TMLINT_LOCK_RE = re.compile(
    r"#\s*tmlint:\s*disable=[^#]*\block-global-mutation\b"
)


# ---------------------------------------------------------------------------
# lock birth sites and owner attribution


def born_locks(pkg: Package):
    """(instance_locks, global_locks): where locks are created.
    instance_locks: (path, class, attr) -> ctor kind;
    global_locks: (path, name) -> ctor kind."""
    instance: Dict[Tuple[str, str, str], str] = {}
    global_: Dict[Tuple[str, str], str] = {}

    def ctor_kind(mod: ModuleIndex, value: ast.AST) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        d = _dotted(value.func)
        if d.startswith("threading.") and d.split(".")[1] in _LOCK_CTORS:
            return d.split(".")[1]
        if d in _LOCK_CTORS:
            entry = mod.from_imports.get(d)
            if entry is not None and entry[1] == "threading":
                return d
        return None

    for mod in pkg.modules.values():
        for node in mod.tree.body:
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                kind = ctor_kind(mod, node.value) if node.value else None
                if kind:
                    for t in targets:
                        if isinstance(t, ast.Name):
                            global_[(mod.path, t.id)] = kind
        for cname, rec in mod.classes.items():
            for m in rec["methods"].values():
                for node in ast.walk(m):
                    if not isinstance(node, ast.Assign):
                        continue
                    kind = ctor_kind(mod, node.value)
                    if not kind:
                        continue
                    for t in node.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            instance[(mod.path, cname, t.attr)] = kind
    return instance, global_


class _Attribution:
    """Resolves `self.<attr>` (and typed receivers) to the class that
    OWNS the attribute — the class in the MRO whose methods assign it —
    so subclass uses share one identity."""

    def __init__(self, pkg: Package) -> None:
        self.pkg = pkg
        self._assign_cache: Dict[Tuple[str, str], Set[str]] = {}
        self._owner_cache: Dict[Tuple[str, str, str], Optional[Tuple[str, str]]] = {}

    def _assigned_attrs(self, mod: ModuleIndex, cname: str) -> Set[str]:
        key = (mod.path, cname)
        got = self._assign_cache.get(key)
        if got is not None:
            return got
        attrs: Set[str] = set()
        rec = mod.classes.get(cname)
        if rec is not None:
            for m in rec["methods"].values():
                for node in ast.walk(m):
                    tgts: List[ast.AST] = []
                    if isinstance(node, ast.Assign):
                        tgts = node.targets
                    elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                        tgts = [node.target]
                    for t in tgts:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            attrs.add(t.attr)
            for item in rec["node"].body:
                if isinstance(item, ast.AnnAssign) and isinstance(
                    item.target, ast.Name
                ):
                    attrs.add(item.target.id)
        self._assign_cache[key] = attrs
        return attrs

    def owner(
        self, mod: ModuleIndex, cname: str, attr: str, _depth: int = 0
    ) -> Optional[Tuple[str, str]]:
        """(path, class) owning `attr` for class `cname` visible in
        `mod`, walking base classes; None when nothing assigns it."""
        ck = (mod.path, cname, attr)
        if ck in self._owner_cache:
            return self._owner_cache[ck]
        out: Optional[Tuple[str, str]] = None
        if _depth <= 4:
            found = self.pkg.find_class(mod, cname)
            if found is not None:
                owner_mod, rec = found
                real = rec["node"].name
                # prefer the deepest BASE that assigns it (shared
                # identity); fall back to this class
                for base in rec["bases"]:
                    base = base.split(".")[-1]
                    got = self.owner(owner_mod, base, attr, _depth + 1)
                    if got is not None:
                        out = got
                        break
                if out is None and attr in self._assigned_attrs(
                    owner_mod, real
                ):
                    out = (owner_mod.path, real)
        self._owner_cache[ck] = out
        return out


# ---------------------------------------------------------------------------
# per-function syntactic summaries


class Access:
    """One shared-state touch: a module global or a `self.` field."""

    __slots__ = ("var", "write", "lineno", "locks", "what")

    def __init__(self, var, write, lineno, locks, what) -> None:
        self.var = var  # ("g", path, name) | ("f", path, class, attr)
        self.write = write
        self.lineno = lineno
        self.locks: FrozenSet[str] = locks  # syntactic (with-enclosed)
        self.what = what  # rendered access form for the message


class WithSite:
    __slots__ = ("lineno", "lock", "outer", "kind")

    def __init__(self, lineno, lock, outer, kind) -> None:
        self.lineno = lineno
        self.lock = lock
        self.outer: FrozenSet[str] = outer
        self.kind = kind  # Lock | RLock | Condition | "" (heuristic)


class FuncSummary:
    __slots__ = (
        "key", "with_sites", "call_locks", "accesses", "convention"
    )

    def __init__(self, key) -> None:
        self.key = key
        self.with_sites: List[WithSite] = []
        # (lineno, col) of a call -> locks syntactically held there
        self.call_locks: Dict[Tuple[int, int], FrozenSet[str]] = {}
        self.accesses: List[Access] = []
        self.convention: FrozenSet[str] = frozenset()


class LockEdge:
    """One held -> acquiring edge derived along some static path."""

    __slots__ = ("held", "acquired", "where", "func")

    def __init__(self, held, acquired, where, func) -> None:
        self.held = held
        self.acquired = acquired
        self.where = where
        self.func = func


class Summarizer:
    """Builds per-function summaries: with-site lock names, per-call
    held sets, and shared-state accesses, with lock names attributed
    per the module docstring."""

    def __init__(self, pkg: Package) -> None:
        self.pkg = pkg
        self.attribution = _Attribution(pkg)
        self.instance_locks, self.global_locks = born_locks(pkg)
        # per-module name sets
        self._module_globals: Dict[str, Set[str]] = {}
        for mod in pkg.modules.values():
            names: Set[str] = set()
            for node in mod.tree.body:
                tgts: List[ast.AST] = []
                if isinstance(node, ast.Assign):
                    tgts = node.targets
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                    tgts = [node.target]
                for t in tgts:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
            self._module_globals[mod.path] = names

    def module_globals(self, path: str) -> Set[str]:
        return self._module_globals.get(path, set())

    # -- lock naming --

    def _is_lock_ctx(self, mod, fi, expr, local_types) -> Optional[str]:
        """The stable lock name for a with-context expression, or None
        when it isn't a lock."""
        d = _dotted(expr)
        if not d and isinstance(expr, ast.Call):
            d = _dotted(expr.func)
        if not d:
            return None
        parts = d.split(".")
        head, attr = parts[0], parts[-1]
        lockish = "lock" in d.lower()
        if len(parts) == 1:
            # bare name: module-level lock global or a local alias
            if (mod.path, head) in self.global_locks:
                return f"{mod.path}:{head}"
            if lockish and head in self.module_globals(mod.path):
                return f"{mod.path}:{head}"
            return WILDCARD if lockish else None
        cname: Optional[str] = None
        if head in ("self", "cls") and len(parts) == 2 and fi.class_name:
            cname = fi.class_name
            cmod = mod
        elif len(parts) == 2 and head in local_types:
            cname = local_types[head]
            cmod = mod
        elif len(parts) == 2 and head in mod.var_class:
            owner, oc = mod.var_class[head]
            cname, cmod = oc, owner
        else:
            # mod-attr: `sigcache._lock` through an import
            entry = mod.from_imports.get(head)
            target = None
            if entry is not None and entry[0] is not None:
                base = entry[0] + "." + entry[2] if entry[0] else entry[2]
                target = self.pkg.module_for_dotted(base)
            if target is not None and len(parts) == 2:
                if (target.path, attr) in self.global_locks or (
                    lockish and attr in self.module_globals(target.path)
                ):
                    return f"{target.path}:{attr}"
            return WILDCARD if lockish else None
        owner = self.attribution.owner(cmod, cname, attr)
        if owner is not None:
            if (owner[0], owner[1], attr) in self.instance_locks:
                return f"{owner[0]}:{owner[1]}.{attr}"
            if lockish:
                return f"{owner[0]}:{owner[1]}.{attr}"
            return None
        return WILDCARD if lockish else None

    def lock_kind(self, name: str) -> str:
        if ":" not in name:
            return ""
        path, rest = name.split(":", 1)
        if "." in rest:
            cname, attr = rest.rsplit(".", 1)
            return self.instance_locks.get((path, cname, attr), "")
        return self.global_locks.get((path, rest), "")

    def _convention(self, mod, fi) -> FrozenSet[str]:
        """`*_locked` naming: the owner's `_lock` is held by contract."""
        leaf = fi.qualname.split(".")[-1]
        if not leaf.endswith("_locked"):
            return frozenset()
        if fi.class_name:
            owner = self.attribution.owner(mod, fi.class_name, "_lock")
            if owner is not None:
                return frozenset({f"{owner[0]}:{owner[1]}._lock"})
        return frozenset({WILDCARD})

    # -- the walker --

    def summarize_function(self, fi: FuncInfo) -> FuncSummary:
        mod = self.pkg.modules[fi.path]
        local_types = self.pkg._local_types(mod, fi.node)
        summary = FuncSummary(fi.key)
        summary.convention = self._convention(mod, fi)
        globals_here = self.module_globals(fi.path)
        is_init = fi.qualname.split(".")[-1] in ("__init__", "__new__")
        methods = (
            set(mod.classes[fi.class_name]["methods"])
            if fi.class_name and fi.class_name in mod.classes
            else set()
        )

        # names bound locally (shadowing module globals for reads).
        # Scope-correct: nested defs/classes are separate scopes (and
        # separate graph nodes, like the access walker treats them) —
        # a nested `global X` must not turn the enclosing function's
        # plain local X into a global write, and a name bound only
        # inside a nested def must not hide the outer function's reads
        # of the same-named module global
        def body_nodes(root: ast.AST):
            stack = list(ast.iter_child_nodes(root))
            while stack:
                node = stack.pop()
                if isinstance(
                    node,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    continue
                yield node
                stack.extend(ast.iter_child_nodes(node))

        declared_global: Set[str] = set()
        bound: Set[str] = set()
        for node in body_nodes(fi.node):
            if isinstance(node, ast.Global):
                declared_global.update(node.names)
        args = fi.node.args
        for a in (
            list(args.args)
            + list(args.posonlyargs)
            + list(args.kwonlyargs)
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        ):
            bound.add(a.arg)
        for node in body_nodes(fi.node):
            tgts: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                tgts = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                tgts = [node.target]
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                tgts = [node.target]
            elif isinstance(node, (ast.withitem,)) and node.optional_vars:
                tgts = [node.optional_vars]
            elif isinstance(node, ast.comprehension):
                tgts = [node.target]
            for t in tgts:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        bound.add(n.id)
        shadowed = bound - declared_global

        def global_var(name: str, for_write: bool) -> Optional[tuple]:
            if name not in globals_here:
                return None
            if for_write and name not in declared_global:
                return None  # a plain assignment makes it local
            if not for_write and name in shadowed:
                return None
            return ("g", fi.path, name)

        def field_var(attr: str) -> Optional[tuple]:
            if not fi.class_name or attr in methods:
                return None
            owner = self.attribution.owner(mod, fi.class_name, attr)
            if owner is None:
                owner = (fi.path, fi.class_name)
            return ("f", owner[0], owner[1], attr)

        def add_access(var, write, node, locks, what):
            if var is None:
                return
            if write and is_init and var[0] == "f":
                return  # single-threaded construction
            summary.accesses.append(
                Access(var, write, node.lineno, locks, what)
            )

        def walk(node: ast.AST, held: FrozenSet[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    continue  # separate graph nodes
                walk_node(child, held)

        def walk_with(child: ast.AST, held: FrozenSet[str]) -> None:
            inner = held
            for item in child.items:
                name = self._is_lock_ctx(
                    mod, fi, item.context_expr, local_types
                )
                walk(item.context_expr, inner)
                if name is not None:
                    kind = (
                        self.lock_kind(name) if name != WILDCARD else ""
                    )
                    summary.with_sites.append(
                        WithSite(
                            item.context_expr.lineno, name, inner, kind
                        )
                    )
                    inner = inner | {name}
            for stmt in child.body:
                walk_node(stmt, inner)

        def walk_node(child: ast.AST, held: FrozenSet[str]) -> None:
            # dispatched for DIRECT and nested statements alike, so a
            # `with b:` inside a `with a:` body still records its site
            # (and the a->b order edge)
            if isinstance(child, (ast.With, ast.AsyncWith)):
                return walk_with(child, held)
            if isinstance(child, ast.Call):
                summary.call_locks[(child.lineno, child.col_offset)] = held
                f = child.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr in _MUTATING_METHODS
                ):
                    recv = f.value
                    if isinstance(recv, ast.Name):
                        add_access(
                            global_var(recv.id, False)
                            if recv.id in globals_here
                            and recv.id not in shadowed
                            else None,
                            True,
                            child,
                            held,
                            f"`{recv.id}.{f.attr}()`",
                        )
                    elif (
                        isinstance(recv, ast.Attribute)
                        and isinstance(recv.value, ast.Name)
                        and recv.value.id == "self"
                    ):
                        add_access(
                            field_var(recv.attr),
                            True,
                            child,
                            held,
                            f"`self.{recv.attr}.{f.attr}()`",
                        )
            elif isinstance(child, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                if isinstance(child, ast.AnnAssign) and child.value is None:
                    return walk(child, held)  # bare annotation, no write
                tgts = (
                    child.targets
                    if isinstance(child, ast.Assign)
                    else [child.target]
                )
                for t in tgts:
                    if isinstance(t, ast.Name):
                        add_access(
                            global_var(t.id, True),
                            True,
                            child,
                            held,
                            f"`{t.id} = ...`",
                        )
                    elif (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        add_access(
                            field_var(t.attr),
                            True,
                            child,
                            held,
                            f"`self.{t.attr} = ...`",
                        )
                    elif isinstance(t, ast.Subscript):
                        v = t.value
                        if isinstance(v, ast.Name):
                            add_access(
                                global_var(v.id, False)
                                if v.id in globals_here
                                and v.id not in shadowed
                                else None,
                                True,
                                child,
                                held,
                                f"`{v.id}[...] = ...`",
                            )
                        elif (
                            isinstance(v, ast.Attribute)
                            and isinstance(v.value, ast.Name)
                            and v.value.id == "self"
                        ):
                            add_access(
                                field_var(v.attr),
                                True,
                                child,
                                held,
                                f"`self.{v.attr}[...] = ...`",
                            )
            elif isinstance(child, ast.Delete):
                for t in child.targets:
                    if isinstance(t, ast.Subscript) and isinstance(
                        t.value, ast.Name
                    ):
                        add_access(
                            global_var(t.value.id, False)
                            if t.value.id in globals_here
                            and t.value.id not in shadowed
                            else None,
                            True,
                            child,
                            held,
                            f"`del {t.value.id}[...]`",
                        )
            elif isinstance(child, ast.Name) and isinstance(
                child.ctx, ast.Load
            ):
                add_access(
                    global_var(child.id, False),
                    False,
                    child,
                    held,
                    f"`{child.id}` read",
                )
            elif (
                isinstance(child, ast.Attribute)
                and isinstance(child.ctx, ast.Load)
                and isinstance(child.value, ast.Name)
                and child.value.id == "self"
            ):
                add_access(
                    field_var(child.attr),
                    False,
                    child,
                    held,
                    f"`self.{child.attr}` read",
                )
            walk(child, held)

        walk(fi.node, frozenset())
        return summary


# ---------------------------------------------------------------------------
# context-sensitive propagation (entry locksets + lock-order edges)


def propagate(
    pkg: Package,
    summaries: Dict[FuncKey, FuncSummary],
    root_keys: List[FuncKey],
):
    """Walk the call graph from every root, tracking the exact lockset
    held at each call. Returns (entry_contexts, edges, truncated):
    entry_contexts maps a function to the distinct entry locksets seen
    (MUST-entry is their intersection); edges are held->acquiring pairs
    along every explored static path."""
    entry_contexts: Dict[FuncKey, List[FrozenSet[str]]] = {}
    edges: Dict[Tuple[str, str], LockEdge] = {}
    truncated = 0
    stack: List[Tuple[FuncKey, FrozenSet[str]]] = [
        (k, frozenset()) for k in root_keys if k in pkg.functions
    ]
    while stack:
        key, held = stack.pop()
        ctxs = entry_contexts.setdefault(key, [])
        if held in ctxs:
            continue
        if len(ctxs) >= MAX_CONTEXTS:
            truncated += 1
            continue
        ctxs.append(held)
        summary = summaries.get(key)
        if summary is None:
            continue
        effective = held | summary.convention
        for site in summary.with_sites:
            held_at = effective | site.outer
            acq = site.lock
            if acq == WILDCARD:
                continue
            for h in held_at:
                if h == WILDCARD:
                    continue
                if h == acq and site.kind == "RLock":
                    continue  # reentrant re-acquire, not an order edge
                edge = (h, acq)
                if edge not in edges:
                    fi = pkg.functions[key]
                    edges[edge] = LockEdge(
                        h,
                        acq,
                        f"{fi.path}:{site.lineno}",
                        f"{fi.path}:{fi.qualname}",
                    )
        for call in pkg.functions[key].calls:
            if call.target is None or call.target not in pkg.functions:
                continue
            at = summary.call_locks.get(
                (call.lineno, call.col), frozenset()
            )
            stack.append((call.target, effective | at))
    return entry_contexts, edges, truncated


# ---------------------------------------------------------------------------
# suppression maps


def suppression_maps(lines: List[str]):
    """(race_ok_lines, guarded_by): 1-based line numbers carrying
    `# tmrace: race-ok` (or a justified tmlint lock-global-mutation
    disable), and lineno -> asserted lock-name strings for
    `# tmrace: guarded-by=`. Comment-block-above placement covers the
    first code line below — the family-wide convention implemented
    once in tmlint.comment_cover_lines."""
    from ..tmlint import comment_cover_lines

    race_ok: Set[int] = set()
    guarded: Dict[int, Set[str]] = {}
    for i, text in enumerate(lines, start=1):
        if _RACE_OK_RE.search(text) or _TMLINT_LOCK_RE.search(text):
            race_ok.update(comment_cover_lines(lines, i, text))
        m = _GUARDED_BY_RE.search(text)
        if m:
            for ln in comment_cover_lines(lines, i, text):
                guarded.setdefault(ln, set()).add(m.group(1))
    return race_ok, guarded


def resolve_guard_name(asserted: str, known: Set[str]) -> str:
    """Match a guarded-by annotation against the known lock universe by
    suffix (`_REG_LOCK`, `CircuitBreaker._lock`); unknown names pass
    through as written so consistent annotations still intersect."""
    for name in sorted(known):
        if name == asserted or name.endswith(":" + asserted) or name.endswith(
            "." + asserted
        ):
            return name
    return asserted
