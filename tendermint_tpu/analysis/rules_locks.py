"""Lock-discipline rules for the threaded device path.

The TPU crypto path grew real threads (breaker probe timers, gather
watchdog workers, sigcache rotation) on top of the single-writer
asyncio core. Two mechanical hazards follow:

- shared module-level state mutated without its lock is a data race
  the GIL only *mostly* hides (check-then-act sequences interleave);
- a non-daemon worker thread blocks process exit — a wedged gather
  watchdog would hang every node shutdown.

These rules make both visible at lint time; lockwatch (the runtime
half of this subsystem) covers what static analysis can't — actual
acquisition *order* across threads.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional

from .tmlint import Module, Rule, Violation, dotted_name, register

_MUTABLE_CTORS = {
    "list",
    "dict",
    "set",
    "collections.deque",
    "deque",
    "collections.defaultdict",
    "defaultdict",
    "collections.OrderedDict",
    "OrderedDict",
}

_MUTATING_METHODS = {
    "append",
    "extend",
    "insert",
    "remove",
    "pop",
    "popitem",
    "clear",
    "add",
    "discard",
    "update",
    "setdefault",
    "appendleft",
    "popleft",
    "sort",
    "reverse",
}

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}


def _is_mutable_literal(node: ast.AST) -> bool:
    if isinstance(
        node, (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)
    ):
        return True
    if isinstance(node, ast.Call):
        return dotted_name(node.func) in _MUTABLE_CTORS
    return False


def _thread_ctor(mod: Module, node: ast.Call) -> Optional[str]:
    """'Thread'/'Timer' when `node` constructs one, else None."""
    name = dotted_name(node.func)
    if name in ("threading.Thread", "threading.Timer"):
        return name.split(".")[1]
    if name in ("Thread", "Timer") and mod.from_imports.get(name) == "threading":
        return name
    return None


@register
class LockDaemonThread(Rule):
    id = "lock-daemon"
    title = "Thread/Timer without daemon=True"
    rationale = (
        "A non-daemon worker blocks interpreter exit: a breaker probe "
        "timer or gather watchdog parked on a wedged device claim "
        "would hang node shutdown forever. Every background thread in "
        "this codebase must be a daemon (threading.Timer takes no "
        "daemon kwarg — assign `t.daemon = True` before start())."
    )

    def applies(self, mod: Module) -> bool:
        return mod.imports_threading

    def check(self, mod: Module) -> Iterator[Violation]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _thread_ctor(mod, node)
            if kind is None:
                continue
            if any(
                kw.arg == "daemon"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in node.keywords
            ):
                continue
            if self._daemon_assigned_later(mod, node):
                continue
            yield self.violation(
                mod,
                node,
                f"threading.{kind} constructed without daemon=True "
                "(and no `<var>.daemon = True` before start()); a "
                "non-daemon worker blocks process exit",
            )

    def _daemon_assigned_later(self, mod: Module, call: ast.Call) -> bool:
        """True when the construction is `t = threading.Timer(...)` (or
        `self.x = ...`) and the enclosing function later assigns
        `t.daemon = True` — the only way to daemonize a Timer."""
        parent = mod.parents.get(call)
        target_name: Optional[str] = None
        target_attr: Optional[str] = None
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            tgt = parent.targets[0]
            if isinstance(tgt, ast.Name):
                target_name = tgt.id
            elif isinstance(tgt, ast.Attribute):
                target_attr = dotted_name(tgt)
        if target_name is None and target_attr is None:
            return False
        scope = mod.enclosing_function(call) or mod.tree
        for node in ast.walk(scope):
            if not isinstance(node, ast.Assign):
                continue
            if not (
                isinstance(node.value, ast.Constant)
                and node.value.value is True
            ):
                continue
            for tgt in node.targets:
                if not (
                    isinstance(tgt, ast.Attribute) and tgt.attr == "daemon"
                ):
                    continue
                if node.lineno < call.lineno:
                    continue
                base = tgt.value
                if target_name is not None and (
                    isinstance(base, ast.Name) and base.id == target_name
                ):
                    return True
                if target_attr is not None and (
                    dotted_name(base) == target_attr
                ):
                    return True
        return False


@register
class LockGlobalMutation(Rule):
    id = "lock-global-mutation"
    title = "module-level mutable state mutated outside a lock"
    rationale = (
        "In a module that imports threading, module-level "
        "dicts/lists/sets are shared across threads; mutating one "
        "outside a `with <lock>:` block is a data race — GIL "
        "atomicity does not cover check-then-act sequences, and the "
        "reference gates exactly this class of bug with `go test "
        "-race`. Mutations are exempt inside a with-block whose "
        "context mentions a lock, inside functions named `*_locked` "
        "(the held-lock calling convention used across crypto/), and "
        "at module import time (single-threaded)."
    )

    def applies(self, mod: Module) -> bool:
        return mod.imports_threading

    def _module_level_mutables(self, mod: Module) -> set:
        names = set()
        for node in mod.tree.body:
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
                value = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
                value = node.value
            else:
                continue
            if not _is_mutable_literal(value):
                continue
            for tgt in targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
        return names

    def _guarded(self, mod: Module, node: ast.AST) -> bool:
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    ctx = dotted_name(item.context_expr)
                    if not ctx and isinstance(item.context_expr, ast.Call):
                        ctx = dotted_name(item.context_expr.func)
                    if "lock" in ctx.lower():
                        return True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if cur.name.endswith("_locked"):
                    return True
            cur = mod.parents.get(cur)
        return False

    def check(self, mod: Module) -> Iterator[Violation]:
        shared = self._module_level_mutables(mod)
        if not shared:
            return
        for node in ast.walk(mod.tree):
            name: Optional[str] = None
            what = ""
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                if (
                    node.func.attr in _MUTATING_METHODS
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in shared
                ):
                    name = node.func.value.id
                    what = f"`{name}.{node.func.attr}()`"
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for tgt in targets:
                    if (
                        isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id in shared
                    ):
                        name = tgt.value.id
                        what = f"`{name}[...] = ...`"
            elif isinstance(node, ast.Delete):
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id in shared
                    ):
                        name = tgt.value.id
                        what = f"`del {name}[...]`"
            if name is None:
                continue
            # import-time mutation (module or class body) is
            # single-threaded setup
            if mod.enclosing_function(node) is None:
                continue
            if self._guarded(mod, node):
                continue
            yield self.violation(
                mod,
                node,
                f"module-level mutable `{name}` mutated ({what}) outside "
                "a `with <lock>:` block in a threading module; "
                "check-then-act races are not GIL-atomic",
            )

        # rebinding a module global from a function body (global X; X = ...)
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name.endswith("_locked"):
                continue
            declared = {
                n
                for stmt in ast.walk(fn)
                if isinstance(stmt, ast.Global)
                for n in stmt.names
                if n in shared
            }
            if not declared:
                continue
            for node in ast.walk(fn):
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for tgt in targets:
                        if (
                            isinstance(tgt, ast.Name)
                            and tgt.id in declared
                            and not self._guarded(mod, node)
                        ):
                            yield self.violation(
                                mod,
                                node,
                                f"module-level mutable `{tgt.id}` rebound "
                                "outside a `with <lock>:` block in a "
                                "threading module",
                            )
