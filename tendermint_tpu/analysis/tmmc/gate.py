"""The `scripts/lint.py --mc` gate section.

Unlike the other nine sections this one is DYNAMIC: it does not read
the package AST, it *runs* the consensus implementation under the
exhaustive explorer for a fixed small config (GATE_CONFIG) within
fixed budgets (GATE_BUDGETS) and converts any invariant violation
into a `tmlint.Violation` anchored at the failed checker's ``def``
line in ``invariants.py`` — so the shared baseline/suppression
machinery (counted fingerprints, `# tmmc: mc-ok`, exit 0/1/2) applies
unchanged.

The baseline ships EMPTY and must stay empty: a model-checking
violation is a consensus-safety bug with a replayable witness, not a
style finding to grandfather. The suppression form exists for the
same reason the others do — a reviewed, justified exception — but
the review bar is "we understand why the model flags this and the
implementation is right", e.g. a deliberate model-horizon artifact.
"""

from __future__ import annotations

import inspect
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..tmlint import Violation, comment_cover_lines
from . import invariants
from .explorer import Budgets, ExploreResult, MCViolation, explore
from .harness import MCConfig

__all__ = [
    "GATE_BUDGETS",
    "GATE_CONFIG",
    "GATE_SEED",
    "MC_BASELINE_NOTE",
    "MC_BASELINE_PATH",
    "RULES",
    "Report",
    "analyze",
    "mc_violations",
    "named_config",
    "new_mc_violations",
    "update_mc_baseline",
]

MC_BASELINE_PATH = os.path.join(os.path.dirname(__file__), "mc_baseline.json")

MC_BASELINE_NOTE = (
    "Accepted model-checking findings, fingerprinted by "
    "rule:path:sha1(source_line)[:12]. This baseline ships EMPTY and "
    "should stay empty: an mc-* finding is a consensus-safety "
    "violation with a replayable witness trace — fix it, or suppress "
    "it with a justified '# tmmc: mc-ok[=<rule>] — why' comment on "
    "the checker in analysis/tmmc/invariants.py."
)

RULES = [
    (
        "mc-agreement",
        "exhaustive exploration found two nodes committing different "
        "block IDs at one height",
    ),
    (
        "mc-validity",
        "exhaustive exploration found a committed block no honest "
        "proposer produced (or the byzantine EVIL block)",
    ),
    (
        "mc-accountability",
        "exhaustive exploration found a detected equivocation with no "
        "pending or committed DuplicateVoteEvidence after a pool "
        "update",
    ),
    (
        "mc-stall",
        "exhaustive exploration found a state with no enabled "
        "transition while nodes are below the target height",
    ),
]

_RULE_CHECKERS = {
    "mc-agreement": invariants.check_agreement,
    "mc-validity": invariants.check_validity,
    "mc-accountability": invariants.check_accountability,
    "mc-stall": invariants.check_stall,
}

# the gate scenario: 4 validators, 2 heights, one equivocating node —
# the acceptance config (ISSUE 19) every future key class runs under
GATE_SEED = 0
GATE_CONFIG = MCConfig(
    n_validators=4,
    target_height=2,
    max_round=1,
    byz=(
        {"behavior": "equivocate", "h_lo": 1, "h_hi": 1, "victim": "mc0"},
    ),
)
# tuned so the in-gate run stays under the tier-1 pin (tests/
# test_tmmc.py asserts wall < 15 s) while still reaching TERMINALS:
# the synchronous two-height commit path is ~55 transitions deep, so
# the depth bound must clear it or commit-conditioned invariants are
# never probed at full height. The budgets are recorded in the report
# stats so "zero violations" always reads as "zero violations within
# this horizon".
GATE_BUDGETS = Budgets(
    max_states=500,
    max_depth=64,
    max_edges=2_500,
    wall_s=12.0,
)


def named_config(name: str) -> Tuple[MCConfig, Budgets, int]:
    """Bankable scenario registry: (config, budgets, seed) by name.
    scripts/fuzz_repro.py --config resolves through here."""
    if name == "gate":
        return GATE_CONFIG, GATE_BUDGETS, GATE_SEED
    if name == "agreement-ab":
        # 2 validators, 1 height: the weakened-quorum A/B scenario —
        # small enough that exhaustion is guaranteed within budget
        return (
            MCConfig(n_validators=2, target_height=1, max_round=1),
            Budgets(max_states=3_000, max_depth=32, max_edges=8_000,
                    wall_s=30.0),
            GATE_SEED,
        )
    if name == "accountability-ab":
        # 2 validators, 1 height, one equivocator: the smallest config
        # where detection AND a pool update both occur — the first
        # commit runs EvidencePool.update, which is exactly when
        # formed evidence must exist. The depth-12 horizon reaches the
        # first commit and is fully exhaustible (~750 states on HEAD),
        # so the A/B witness is guaranteed to be found, not sampled.
        return (
            MCConfig(
                n_validators=2,
                target_height=1,
                max_round=1,
                byz=(
                    {
                        "behavior": "equivocate",
                        "h_lo": 1,
                        "h_hi": 1,
                        "victim": "mc0",
                    },
                ),
            ),
            Budgets(max_states=20_000, max_depth=12, max_edges=60_000,
                    wall_s=45.0),
            GATE_SEED,
        )
    raise KeyError(f"unknown tmmc config {name!r}; "
                   f"known: gate, agreement-ab, accountability-ab")


@dataclass
class Report:
    violations: List[Violation] = field(default_factory=list)
    mc: List[MCViolation] = field(default_factory=list)
    stats: Dict[str, Any] = field(default_factory=dict)
    suppressed: int = 0


_MC_OK_RE = re.compile(r"#\s*tmmc:\s*mc-ok(?:=([A-Za-z0-9_\-, ]+))?")


def _suppressions() -> Dict[int, Optional[set]]:
    """Line -> rule-set (None = all rules) covered by a `# tmmc:
    mc-ok` annotation in invariants.py, using the family-shared
    comment-block convention."""
    src = inspect.getsource(invariants)
    lines = src.splitlines()
    covered: Dict[int, Optional[set]] = {}
    for i, text in enumerate(lines, start=1):
        m = _MC_OK_RE.search(text)
        if not m:
            continue
        named = (
            {r.strip() for r in m.group(1).split(",") if r.strip()}
            if m.group(1)
            else None
        )
        for ln in comment_cover_lines(lines, i, text):
            prev = covered.get(ln, set())
            if prev is None or named is None:
                covered[ln] = None
            else:
                covered[ln] = prev | named
    return covered


def _anchor(rule: str) -> Tuple[str, int, str]:
    """(relative path, def line, def source) of the rule's checker —
    the stable code location a finding and its suppression share."""
    fn = _RULE_CHECKERS[rule]
    lines, lineno = inspect.getsourcelines(fn)
    return "analysis/tmmc/invariants.py", lineno, lines[0].rstrip("\n")


def _to_violations(result: ExploreResult) -> Tuple[List[Violation], int]:
    covered = _suppressions()
    out: List[Violation] = []
    suppressed = 0
    for mcv in result.violations:
        path, line, source = _anchor(mcv.rule)
        named = covered.get(line, "absent")
        if named != "absent" and (named is None or mcv.rule in named):
            suppressed += 1
            continue
        trace = mcv.trace
        out.append(
            Violation(
                rule=mcv.rule,
                path=path,
                line=line,
                col=0,
                message=(
                    f"{mcv.message} — replay: scripts/fuzz_repro.py "
                    f"--config gate --seed {trace.seed} "
                    f"(trace depth {len(trace.transitions)})"
                ),
                source=source,
            )
        )
    return out, suppressed


def analyze(
    config: Optional[MCConfig] = None,
    budgets: Optional[Budgets] = None,
    seed: Optional[int] = None,
) -> Report:
    result = explore(
        config if config is not None else GATE_CONFIG,
        budgets if budgets is not None else GATE_BUDGETS,
        seed=seed if seed is not None else GATE_SEED,
        stop_at_first=False,
    )
    violations, suppressed = _to_violations(result)
    return Report(
        violations=violations,
        mc=result.violations,
        stats=result.stats,
        suppressed=suppressed,
    )


def mc_violations(report: Optional[Report] = None) -> List[Violation]:
    return (report if report is not None else analyze()).violations


def new_mc_violations(
    report: Optional[Report] = None,
    baseline_path: Optional[str] = None,
) -> List[Violation]:
    from ..tmlint import load_baseline, new_violations

    violations = mc_violations(report)
    baseline = load_baseline(baseline_path or MC_BASELINE_PATH)
    return new_violations(violations, baseline)


def update_mc_baseline(
    report: Optional[Report] = None,
    baseline_path: Optional[str] = None,
) -> Dict[str, int]:
    from ..tmlint import save_baseline

    return save_baseline(
        mc_violations(report),
        baseline_path or MC_BASELINE_PATH,
        note=MC_BASELINE_NOTE,
    )
