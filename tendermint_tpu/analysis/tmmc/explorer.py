"""Stateless DFS explorer over ModelNet schedules.

Exploration is *stateless* in the model-checking sense: there is one
live ModelNet; descending applies transitions to it, and backtracking
re-executes the target prefix from a fresh net (counted in
``stats["replays"]``). Signature memoization in the harness makes
re-execution cheap — the ed25519 cost is paid once per distinct
message for the whole exploration.

Reduction is two-layered:

- **Sleep sets** (partial-order reduction): transitions on different
  nodes commute — a node's transition mutates only that node plus
  append-only ``pending`` sets at peers, and purge/enabledness at a
  node depend only on that node's own round-state — so a sibling
  already explored at state ``s`` is not re-explored under a child
  reached by an independent transition.
- **Fingerprint dedup**: a SHA-1 over every node's round-state,
  vote sets, commit chain, evidence, pending sets and adversary
  record; a revisited fingerprint prunes the whole subtree.

Both are exhaustive *within the budgets* (depth/states/edges/wall and
the config's round cap); the gate reports the budgets alongside the
result so "zero violations" is always read as "zero violations within
this recorded horizon". Combining dedup with sleep sets can prune a
re-entry path whose sleep set differs — the budgets, not the dedup,
are already the soundness boundary here, and the naive mode exists to
measure exactly what the reduction buys (``measure_reduction``).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ...libs.schedulefuzz import Schedule
from .harness import MCConfig, ModelNet

Transition = Tuple
_CheckFn = Callable[[ModelNet, List[Transition]], List[Tuple[str, str]]]


def _default_check(net: ModelNet, enabled: List[Transition]):
    from . import invariants

    return invariants.check_all(net, enabled)


# ---------------------------------------------------------------------------
# results


@dataclass
class Budgets:
    """Exploration horizon. All four are hard caps; whichever trips
    first is recorded in stats["stopped_by"]."""

    max_states: int = 20_000
    max_depth: int = 64
    max_edges: int = 60_000
    wall_s: float = 60.0

    def describe(self) -> Dict[str, Any]:
        return {
            "max_states": self.max_states,
            "max_depth": self.max_depth,
            "max_edges": self.max_edges,
            "wall_s": self.wall_s,
        }


@dataclass
class Trace:
    """A replayable witness: config + seed + explicit transition list.
    ``transitions`` round-trips through JSON as nested lists;
    ``replay_trace`` re-executes it deterministically."""

    seed: int
    config: Dict[str, Any]
    transitions: List[Transition]
    rule: str = ""
    message: str = ""

    def to_json(self) -> Dict[str, Any]:
        return {
            "seed": self.seed,
            "config": self.config,
            "transitions": [list(t) for t in self.transitions],
            "rule": self.rule,
            "message": self.message,
        }

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "Trace":
        return cls(
            seed=int(d["seed"]),
            config=dict(d["config"]),
            transitions=[_tuplify(t) for t in d["transitions"]],
            rule=d.get("rule", ""),
            message=d.get("message", ""),
        )


def _tuplify(x):
    return tuple(_tuplify(i) for i in x) if isinstance(x, list) else x


@dataclass
class MCViolation:
    rule: str
    message: str
    trace: Trace


@dataclass
class ExploreResult:
    violations: List[MCViolation]
    stats: Dict[str, Any]

    @property
    def ok(self) -> bool:
        return not self.violations


# ---------------------------------------------------------------------------
# DFS core


@dataclass
class _Frame:
    path: List[Transition]
    todo: List[Transition]
    sleep: frozenset
    done: List[Transition] = field(default_factory=list)
    next_i: int = 0


class _Explorer:
    def __init__(
        self,
        cfg: MCConfig,
        budgets: Budgets,
        seed: int,
        check: Optional[_CheckFn],
        reduce: bool = True,
        dedup: bool = True,
        stop_at_first: bool = True,
        target_unique: Optional[int] = None,
    ) -> None:
        self.cfg = cfg
        self.budgets = budgets
        self.seed = seed
        self.check = check if check is not None else _default_check
        self.reduce = reduce
        self.dedup = dedup
        self.stop_at_first = stop_at_first
        self.target_unique = target_unique
        self.loop = asyncio.new_event_loop()
        self.memos: List[Dict[bytes, bytes]] = [
            {} for _ in range(cfg.n_validators)
        ]
        self.sched = Schedule(seed)
        self.net = ModelNet(cfg, self.loop, self.memos)
        self.cur_path: List[Transition] = []
        self.seen: set = set()
        self.violations: List[MCViolation] = []
        self.stats: Dict[str, Any] = {
            "states": 0,
            "edges": 0,
            "replays": 0,
            "replay_steps": 0,
            "dedup_hits": 0,
            "sleep_skips": 0,
            "terminals": 0,
            "pruned_round_cap": 0,
            "suppressed_done": 0,
            "max_depth_seen": 0,
            "unique_fingerprints": 0,
            "stopped_by": "exhausted",
        }

    def close(self) -> None:
        self.net.close()
        self.loop.close()

    # -- replay machinery ---------------------------------------------

    def _goto(self, path: List[Transition]) -> None:
        cur = self.cur_path
        if len(path) >= len(cur) and path[: len(cur)] == cur:
            suffix = path[len(cur) :]
        else:
            self.net.close()
            self.net = ModelNet(self.cfg, self.loop, self.memos)
            self.stats["replays"] += 1
            self.stats["replay_steps"] += len(path)
            suffix = path
        for t in suffix:
            self.net.apply(t)
        self.cur_path = list(path)

    # -- expansion ----------------------------------------------------

    def _order(self, children: List[Transition], depth: int) -> List[Transition]:
        """Schedule-seeded child order, deliveries before timeouts.

        The partition is a search heuristic, not a restriction: DFS
        still explores every child. Putting deliveries first means the
        first dive follows the synchronous happy path — commits happen
        within a few dozen transitions, so commit-conditioned
        invariants (agreement, accountability) are probed immediately
        instead of after the timeout-heavy asynchronous subtrees."""
        label = f"mc:{depth}:{self.stats['states']}"
        sched = Schedule(self.sched.subseed(label))
        deliveries = sched.shuffled(sorted(t for t in children if t[0] == "d"))
        timeouts = sched.shuffled(sorted(t for t in children if t[0] == "t"))
        return deliveries + timeouts

    def _record_violations(
        self, found: List[Tuple[str, str]], path: List[Transition]
    ) -> None:
        for rule, message in found:
            self.violations.append(
                MCViolation(
                    rule=rule,
                    message=message,
                    trace=Trace(
                        seed=self.seed,
                        config=self.cfg.describe(),
                        transitions=list(path),
                        rule=rule,
                        message=message,
                    ),
                )
            )

    def run(self) -> ExploreResult:
        t0 = time.perf_counter()
        st = self.stats
        net = self.net
        try:
            # root state — always recorded in ``seen``: the dedup flag
            # controls subtree pruning, not unique-state bookkeeping
            # (naive-mode coverage counts must be comparable)
            self.seen.add(net.fingerprint())
            st["states"] += 1
            enabled = net.transitions()
            st["pruned_round_cap"] += net.pruned_round_cap
            st["suppressed_done"] += net.suppressed_done
            self._record_violations(self.check(net, enabled), [])
            if self.violations and self.stop_at_first:
                st["stopped_by"] = "violation"
                return ExploreResult(self.violations, self._finish(st, t0))
            stack = [_Frame(path=[], todo=self._order(enabled, 0), sleep=frozenset())]

            while stack:
                if time.perf_counter() - t0 > self.budgets.wall_s:
                    st["stopped_by"] = "wall_s"
                    break
                if st["states"] >= self.budgets.max_states:
                    st["stopped_by"] = "max_states"
                    break
                if st["edges"] >= self.budgets.max_edges:
                    st["stopped_by"] = "max_edges"
                    break
                frame = stack[-1]
                if frame.next_i >= len(frame.todo):
                    stack.pop()
                    continue
                t = frame.todo[frame.next_i]
                frame.next_i += 1
                if self.reduce and t in frame.sleep:
                    st["sleep_skips"] += 1
                    continue
                explored_before = list(frame.done)
                frame.done.append(t)
                self._goto(frame.path)
                self.net.apply(t)
                net = self.net
                st["edges"] += 1
                path = frame.path + [t]
                self.cur_path = path
                st["max_depth_seen"] = max(st["max_depth_seen"], len(path))
                fp = net.fingerprint()
                if fp in self.seen:
                    st["dedup_hits"] += 1
                    if self.dedup:
                        continue
                self.seen.add(fp)
                st["states"] += 1
                if (
                    self.target_unique is not None
                    and len(self.seen) >= self.target_unique
                ):
                    st["stopped_by"] = "coverage"
                    break
                enabled = net.transitions()
                st["pruned_round_cap"] += net.pruned_round_cap
                st["suppressed_done"] += net.suppressed_done
                found = self.check(net, enabled)
                if found:
                    self._record_violations(found, path)
                    if self.stop_at_first:
                        st["stopped_by"] = "violation"
                        break
                if net.all_done():
                    st["terminals"] += 1
                    continue
                if len(path) >= self.budgets.max_depth:
                    continue
                if self.reduce:
                    enabled_set = set(enabled)
                    child_sleep = frozenset(
                        x
                        for x in (set(frame.sleep) | set(explored_before))
                        if x[1] != t[1] and x in enabled_set
                    )
                else:
                    child_sleep = frozenset()
                stack.append(
                    _Frame(
                        path=path,
                        todo=self._order(enabled, len(path)),
                        sleep=child_sleep,
                    )
                )
        finally:
            self.close()
        return ExploreResult(self.violations, self._finish(st, t0))

    def _finish(self, st: Dict[str, Any], t0: float) -> Dict[str, Any]:
        st["wall_s"] = round(time.perf_counter() - t0, 3)
        st["unique_fingerprints"] = len(self.seen)
        st["seed"] = self.seed
        st["budgets"] = self.budgets.describe()
        st["config"] = self.cfg.describe()
        st["reduce"] = self.reduce
        st["dedup"] = self.dedup
        return st


# ---------------------------------------------------------------------------
# public API


def explore(
    cfg: MCConfig,
    budgets: Optional[Budgets] = None,
    seed: int = 0,
    check: Optional[_CheckFn] = None,
    reduce: bool = True,
    dedup: bool = True,
    stop_at_first: bool = True,
    target_unique: Optional[int] = None,
) -> ExploreResult:
    """Exhaustively explore ``cfg`` within ``budgets``. On violation,
    each MCViolation carries a replayable Trace; reproduce with::

        python scripts/fuzz_repro.py --trace trace.json
    """
    ex = _Explorer(
        cfg,
        budgets or Budgets(),
        seed,
        check,
        reduce=reduce,
        dedup=dedup,
        stop_at_first=stop_at_first,
        target_unique=target_unique,
    )
    return ex.run()


def _replay(
    cfg: MCConfig,
    transitions: List[Transition],
    check: Optional[_CheckFn] = None,
) -> Tuple[Optional[ModelNet], List[Tuple[str, str]], bool]:
    """Apply ``transitions`` on a fresh net. Returns (net, violations
    found at any prefix, all_enabled). The caller must ``close()`` the
    returned net (and its loop via net.loop)."""
    check = check if check is not None else _default_check
    loop = asyncio.new_event_loop()
    net = ModelNet(cfg, loop)
    found: List[Tuple[str, str]] = []
    seen_rules: set = set()

    def _check_now() -> None:
        enabled = net.transitions()
        for rule, message in check(net, enabled):
            if rule not in seen_rules:
                seen_rules.add(rule)
                found.append((rule, message))

    _check_now()
    for t in transitions:
        enabled = net.transitions()
        if t not in enabled:
            return net, found, False
        net.apply(t)
        _check_now()
    return net, found, True


def replay_trace(
    trace: Trace, check: Optional[_CheckFn] = None
) -> Tuple[ModelNet, List[Tuple[str, str]], bool]:
    """Re-execute a Trace. Returns (net, violations, complete). The
    net is live (timelines, stores, evidence pools inspectable);
    callers must ``net.close()`` and ``net.loop.close()``."""
    cfg = MCConfig(
        n_validators=trace.config["n_validators"],
        target_height=trace.config["target_height"],
        max_round=trace.config["max_round"],
        byz=tuple(dict(s) for s in trace.config.get("byz", ())),
    )
    return _replay(cfg, list(trace.transitions), check)


def minimize_trace(
    trace: Trace,
    check: Optional[_CheckFn] = None,
    max_passes: int = 4,
) -> Trace:
    """Greedy delta-debugging: repeatedly drop single transitions (in
    reverse order) while the replay still reaches a violation of the
    same rule with every remaining transition enabled."""

    def _still_fails(transitions: List[Transition]) -> bool:
        net, found, complete = _replay(
            _cfg_of(trace), transitions, check
        )
        net.close()
        net.loop.close()
        return complete and any(rule == trace.rule for rule, _ in found)

    best = list(trace.transitions)
    for _ in range(max_passes):
        shrunk = False
        i = len(best) - 1
        while i >= 0:
            candidate = best[:i] + best[i + 1 :]
            if _still_fails(candidate):
                best = candidate
                shrunk = True
            i -= 1
        if not shrunk:
            break
    return Trace(
        seed=trace.seed,
        config=trace.config,
        transitions=best,
        rule=trace.rule,
        message=trace.message,
    )


def _cfg_of(trace: Trace) -> MCConfig:
    return MCConfig(
        n_validators=trace.config["n_validators"],
        target_height=trace.config["target_height"],
        max_round=trace.config["max_round"],
        byz=tuple(dict(s) for s in trace.config.get("byz", ())),
    )


def measure_reduction(
    cfg: MCConfig,
    budgets: Optional[Budgets] = None,
    seed: int = 0,
    naive_edge_factor: float = 12.0,
    naive_wall_s: float = 120.0,
) -> Dict[str, Any]:
    """Exhausted-horizon comparison of reduced vs naive enumeration.

    The reduced run (sleep sets + dedup) must EXHAUST its horizon —
    use a budget whose depth bound is reachable (the gate/bench budget
    is tuned for this). Its unique-fingerprint count is then the
    complete coverage of that subspace. The naive run (no sleep sets,
    no dedup pruning) re-enumerates the same subspace path by path and
    stops as soon as it has *seen* every state the reduced run covered
    (``stopped_by == "coverage"``), exhausts the tree itself, or burns
    ``naive_edge_factor`` times the reduced edge count / the wall cap
    without getting there — whichever is first.

    Two ratios at that point:

        reduction_x (= states_x) = naive state visits / reduced state
                                   visits — the classic POR metric
        edges_x                  = naive edges / reduced edges

    When the naive run matched coverage or exhausted, the ratios are
    exact for that horizon; otherwise (``coverage_matched`` False,
    ``reduction_lower_bound`` True) they are lower bounds: even that
    much naive effort did not reproduce what the reduced run covered
    exhaustively.
    """
    budgets = budgets or Budgets()
    reduced = explore(
        cfg, budgets, seed=seed, reduce=True, dedup=True,
        stop_at_first=False,
    )
    target = reduced.stats["unique_fingerprints"]
    naive_budget = Budgets(
        max_states=10**9,
        max_depth=budgets.max_depth,
        max_edges=int(reduced.stats["edges"] * naive_edge_factor),
        wall_s=naive_wall_s,
    )
    naive = explore(
        cfg,
        naive_budget,
        seed=seed,
        reduce=False,
        dedup=False,
        stop_at_first=False,
        target_unique=target,
    )
    matched = naive.stats["unique_fingerprints"] >= target
    exact = matched or naive.stats["stopped_by"] == "exhausted"
    states_x = naive.stats["states"] / max(1, reduced.stats["states"])
    edges_x = naive.stats["edges"] / max(1, reduced.stats["edges"])
    return {
        "reduced": reduced.stats,
        "naive": naive.stats,
        "reduced_exhausted": reduced.stats["stopped_by"] == "exhausted",
        "coverage_matched": matched,
        "reduction_lower_bound": not exact,
        "reduction_x": round(states_x, 2),
        "edges_x": round(edges_x, 2),
    }
