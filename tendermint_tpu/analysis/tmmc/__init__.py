"""tmmc — exhaustive consensus exploration (stateless model checking).

The ninth..first gate sections prove DATAFLOW properties (determinism,
taint, races, cost) by reading the package; the chaos/byzantine
campaigns SAMPLE schedules at random. This package closes the gap
between them: it exhaustively explores vote/proposal/part/timeout
delivery interleavings of the REAL consensus implementation — the
actual `consensus/state.py` ConsensusState objects, not an abstract
model — for small configs (2-4 validators, 1-3 heights), with the
PR-18 byzantine behavior catalog (`consensus/byzantine.py`) composed
in as adversary transitions, so the explored space includes lying
nodes and not just reordering.

Pieces:

- `harness`   — ModelNet: N in-process validators whose network and
                timers are lifted into an explicit pending set; a
                transition is "deliver one pending message" or "fire
                one pending timeout". schedulefuzz's Schedule seam
                supplies the deterministic enumeration order (the same
                seed-discipline the random campaigns bank).
- `explorer`  — DFS with sleep-set partial-order reduction and
                state-fingerprint dedup (round-state + vote-set +
                commit-hash fingerprints), depth/state/edge/wall
                budgets, a naive mode for measuring the reduction, and
                greedy trace minimization.
- `invariants`— agreement, validity, accountability, stall-freedom —
                checked at EVERY explored state; any violation emits a
                minimized, replayable trace (seed + transition list)
                that `replay_trace` re-executes deterministically and
                the PR-15 flight recorder renders as a per-height
                story (scripts/fuzz_repro.py).
- `gate`      — the `scripts/lint.py --mc` section: exit 0/1/2, a
                counted fingerprint baseline shipped EMPTY
                (mc_baseline.json), suppression form `# tmmc: mc-ok`,
                refusal-matrix parity with the other update modes.

docs/static_analysis.md ("Exhaustive exploration") has the state
model, the reduction argument, the invariant table, and the
trace-replay cookbook.
"""

from .explorer import (  # noqa: F401
    Budgets,
    ExploreResult,
    MCViolation,
    Trace,
    explore,
    measure_reduction,
    minimize_trace,
    replay_trace,
)
from .gate import (  # noqa: F401
    GATE_BUDGETS,
    GATE_CONFIG,
    GATE_SEED,
    MC_BASELINE_NOTE,
    MC_BASELINE_PATH,
    RULES,
    Report,
    analyze,
    mc_violations,
    named_config,
    new_mc_violations,
    update_mc_baseline,
)
from .harness import MCConfig, ModelNet  # noqa: F401

__all__ = [
    "Budgets",
    "ExploreResult",
    "GATE_BUDGETS",
    "GATE_CONFIG",
    "GATE_SEED",
    "MCConfig",
    "MCViolation",
    "MC_BASELINE_NOTE",
    "MC_BASELINE_PATH",
    "ModelNet",
    "RULES",
    "Report",
    "Trace",
    "analyze",
    "explore",
    "mc_violations",
    "measure_reduction",
    "minimize_trace",
    "named_config",
    "new_mc_violations",
    "replay_trace",
    "update_mc_baseline",
]
