"""ModelNet — N real ConsensusState instances with the network and the
clock lifted into explicit, enumerable transition sets.

The model checker needs three things the live node assembly hides:

1. **Explicit nondeterminism.** Every inter-node message (vote,
   proposal, block part) lands in the *receiver's* ``pending`` dict
   instead of a socket; every scheduled timeout parks in a single
   per-node slot instead of an asyncio timer. A transition is "deliver
   one pending message to one node" or "fire one pending timeout" —
   nothing else moves the system.

2. **Determinism under re-execution.** Stateless exploration replays
   prefixes from the root thousands of times, so every wallclock read
   in the hot path is replaced: ``cs._vote_time`` becomes a per-node
   logical clock, MemoPV pins proposal timestamps from the same clock
   (MockPV would stamp ``time.time_ns()``), and ed25519 signing — the
   dominant cost at ~0.5 ms/signature — is memoized per validator
   across replays keyed by sign-bytes.

3. **The real adversary.** Byzantine behavior is NOT re-modeled: the
   PR-18 ``consensus/byzantine.py`` catalog is armed via its own
   ``inject()`` seam and installed with its own ``maybe_install()``
   against a duck-typed ``_ModelReactor``, so the lies the checker
   explores are byte-for-byte the lies the chaos campaigns send.
   Model configs restrict rules to p=1.0 / times=None so firing is a
   pure function of (height, round, step) and replays are exact.

Message-loss modeling: the model delivers messages at most once and
never drops an *enabled* one, but purges messages the receiver can no
longer use (past-height votes, stale proposals, duplicate parts).
Future-height/round messages are *held* (disabled, not purged) until
the receiver catches up — this models the real reactor's catchup
gossip, which re-offers state a late peer missed; consuming such a
message as a no-op would instead model unrecoverable loss and produce
stall artifacts the real network cannot exhibit.
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ...abci import KVStoreApplication, LocalClient
from ...config import ConsensusConfig, MempoolConfig
from ...consensus import ConsensusState, RoundStep
from ...consensus import byzantine
from ...consensus.msgs import (
    BlockPartMessage,
    MsgInfo,
    ProposalMessage,
    TimeoutInfo,
    VoteMessage,
)
from ...consensus.timeline import TimelineRecorder
from ...crypto.ed25519 import PrivKeyEd25519
from ...evidence.pool import EvidencePool
from ...mempool import TxMempool
from ...privval import MockPV
from ...state import StateStore, state_from_genesis
from ...state.execution import BlockExecutor
from ...store.block_store import BlockStore
from ...store.kv import MemKV
from ...types.genesis import GenesisDoc, GenesisValidator

MC_CHAIN_ID = "tmmc-chain"
_GENESIS_TIME_NS = 1_700_000_000_000_000_000
_MS = 1_000_000  # ns


def _h8(b: Optional[bytes]) -> str:
    """Short stable hex tag for hashes inside transition keys."""
    return b.hex()[:12] if b else "nil"


# ---------------------------------------------------------------------------
# configuration


@dataclass(frozen=True)
class MCConfig:
    """One model-checking scenario: validators, horizon, adversary."""

    n_validators: int = 4
    target_height: int = 2
    max_round: int = 1
    power: int = 10
    # byzantine rule specs: kwargs for byzantine.inject(); the victim
    # moniker must be one of mc0..mc{n-1}
    byz: Tuple[Dict[str, Any], ...] = ()
    chain_id: str = MC_CHAIN_ID

    def __post_init__(self) -> None:
        if self.n_validators < 1:
            raise ValueError("n_validators must be >= 1")
        if self.target_height < 1:
            raise ValueError("target_height must be >= 1")
        if self.max_round < 0:
            raise ValueError("max_round must be >= 0")
        for spec in self.byz:
            if spec.get("p", 1.0) != 1.0 or spec.get("times") is not None:
                # probabilistic/counted rules carry module-global rng +
                # fired state across re-executions; the checker needs
                # firing to be a pure function of (height, round, step)
                raise ValueError(
                    "model-checked byz rules must be deterministic: "
                    f"p=1.0 and times=None required, got {spec!r}"
                )
            victim = spec.get("victim", "load1")
            if not (victim.startswith("mc") and victim[2:].isdigit()):
                raise ValueError(
                    f"byz victim must be an mc<N> moniker, got {victim!r}"
                )

    def describe(self) -> Dict[str, Any]:
        return {
            "n_validators": self.n_validators,
            "target_height": self.target_height,
            "max_round": self.max_round,
            "byz": [dict(s) for s in self.byz],
        }


# key derivation + genesis are pure functions of (n, power, chain) and
# get rebuilt on every backtrack replay — memoized for the exploration
# lifetime. tmlive: bounded= keyed by distinct MCConfig shapes, a
# handful per process
_KEYGEN_CACHE: Dict[Tuple[int, int, str], Tuple[list, GenesisDoc]] = {}


def _keys_and_genesis(n: int, power: int, chain_id: str):
    cached = _KEYGEN_CACHE.get((n, power, chain_id))
    if cached is None:
        privs = [
            PrivKeyEd25519.from_seed(bytes([i + 1]) * 32) for i in range(n)
        ]
        genesis = GenesisDoc(
            chain_id=chain_id,
            genesis_time_ns=_GENESIS_TIME_NS,
            validators=[
                GenesisValidator(pub_key=p.pub_key(), power=power)
                for p in privs
            ],
        )
        cached = (privs, genesis)
        # tmct: ct-ok — deterministic model-checker fixture keys
        # (seeds are the literal bytes([i+1])*32 above), cached so
        # thousands of explored schedules share one keygen; they are
        # not operational key material
        _KEYGEN_CACHE[(n, power, chain_id)] = cached
    return cached


def _mc_consensus_config() -> ConsensusConfig:
    # durations are irrelevant (the stub ticker never sleeps); the
    # flags that change step logic are what matter
    return ConsensusConfig(
        timeout_propose=0.1,
        timeout_propose_delta=0.0,
        timeout_prevote=0.1,
        timeout_prevote_delta=0.0,
        timeout_precommit=0.1,
        timeout_precommit_delta=0.0,
        timeout_commit=0.01,
        skip_timeout_commit=True,
    )


# ---------------------------------------------------------------------------
# determinism shims


class _StubTicker:
    """Ticker twin that parks the newest timeout in ``node.pending_timeout``
    instead of arming an asyncio timer (same replacement discipline as
    consensus/ticker.py TimeoutTicker.schedule)."""

    def __init__(self, node: "ModelNode") -> None:
        self._node = node

    def schedule(self, ti: TimeoutInfo) -> None:
        cur = self._node.pending_timeout
        if cur is not None:
            if ti.height < cur.height:
                return
            if ti.height == cur.height:
                if ti.round < cur.round:
                    return
                if (
                    ti.round == cur.round
                    and cur.step > 0
                    and ti.step <= cur.step
                ):
                    return
        self._node.pending_timeout = ti

    async def start(self) -> None:  # Service duck-typing; never used
        return None

    async def stop(self) -> None:
        self._node.pending_timeout = None


class MemoPV(MockPV):
    """MockPV with (a) logical proposal timestamps and (b) signature
    memoization across replays.

    MockPV stamps ``time.time_ns()`` into zero-timestamp proposals,
    which would make every re-executed prefix diverge; votes are
    already pinned because the harness patches ``cs._vote_time``.
    The memo dict is per-validator and owned by the explorer so the
    ~0.5 ms ed25519 signing cost is paid once per distinct message
    across the whole exploration, not once per replay.
    """

    def __init__(self, priv, clock, memo: Dict[bytes, bytes]) -> None:
        super().__init__(priv)
        self._clock = clock
        self._memo = memo

    async def sign_vote(self, chain_id: str, vote) -> None:
        sb = vote.sign_bytes(chain_id)
        sig = self._memo.get(sb)
        if sig is None:
            sig = self.priv_key.sign(sb)
            self._memo[sb] = sig
        vote.signature = sig

    async def sign_proposal(self, chain_id: str, proposal) -> None:
        if proposal.timestamp_ns == 0:
            proposal.timestamp_ns = self._clock()
        sb = proposal.sign_bytes(chain_id)
        sig = self._memo.get(sb)
        if sig is None:
            sig = self.priv_key.sign(sb)
            self._memo[sb] = sig
        proposal.signature = sig


# ---------------------------------------------------------------------------
# adversary adapter


class _ModelChannel:
    """Duck-typed p2p channel: ByzantineHarness.try_send lands the evil
    message straight in the target node's pending set."""

    def __init__(self, net: "ModelNet") -> None:
        self._net = net

    def try_send(self, env) -> bool:
        self._net._enqueue_for(env.to, env.message)
        return True


class _ModelReactor:
    """The slice of ConsensusReactor that byzantine.ByzantineHarness
    touches: ``.peers`` for target selection, ``.vote_ch``/``.data_ch``
    for sending."""

    def __init__(self, net: "ModelNet", node: "ModelNode") -> None:
        self.peers = [
            n.moniker for n in net.nodes if n.moniker != node.moniker
        ]
        self.vote_ch = _ModelChannel(net)
        self.data_ch = _ModelChannel(net)


# ---------------------------------------------------------------------------
# nodes


@dataclass
class ModelNode:
    index: int
    moniker: str
    priv: Any
    cs: ConsensusState
    evpool: EvidencePool
    block_store: BlockStore
    state_store: StateStore
    timeline: TimelineRecorder
    # pending[key] = message object; key encodes identity so duplicate
    # gossip collapses (setdefault) and evil twins stay distinct
    pending: Dict[Tuple, Any] = field(default_factory=dict)
    pending_timeout: Optional[TimeoutInfo] = None
    clock_ns: int = 0
    # (equivocator height, equivocator addr tag, local store height at
    # detection) — written by the evpool spy, read by accountability
    detections: List[Tuple[int, str, int]] = field(default_factory=list)
    byz_harness: Optional[Any] = None

    def done(self, target_height: int) -> bool:
        return self.block_store.height() >= target_height

    def _vote_time(self) -> int:
        st = self.cs.state
        floor = (
            st.last_block_time_ns + _MS
            if st is not None and st.last_block_time_ns > 0
            else _GENESIS_TIME_NS + _MS
        )
        self.clock_ns = max(self.clock_ns + _MS, floor)
        return self.clock_ns


# ---------------------------------------------------------------------------
# the net


class ModelNet:
    """N-validator model universe. Mutated only through ``apply()``;
    rebuilt from scratch (same cfg) when the explorer backtracks past
    the current path."""

    def __init__(
        self,
        cfg: MCConfig,
        loop: asyncio.AbstractEventLoop,
        sign_memos: Optional[List[Dict[bytes, bytes]]] = None,
    ) -> None:
        self.cfg = cfg
        self.loop = loop
        self.sign_memos = (
            sign_memos
            if sign_memos is not None
            else [{} for _ in range(cfg.n_validators)]
        )
        self.nodes: List[ModelNode] = []
        self._by_moniker: Dict[str, ModelNode] = {}
        # block hashes produced by any honest proposer (validity set)
        self.proposed: set = set()
        # enumeration bookkeeping from the last transitions() call
        self.pruned_round_cap = 0
        self.suppressed_done = 0
        self._byz_stack = contextlib.ExitStack()
        self._closed = False
        self._build()

    # -- construction -------------------------------------------------

    def _build(self) -> None:
        cfg = self.cfg
        privs, genesis = _keys_and_genesis(
            cfg.n_validators, cfg.power, cfg.chain_id
        )
        byzantine.reset()
        for spec in cfg.byz:
            self._byz_stack.enter_context(byzantine.inject(**spec))

        for i, priv in enumerate(privs):
            moniker = f"mc{i}"
            app = KVStoreApplication()
            client = LocalClient(app)
            state_store = StateStore(MemKV())
            state = state_from_genesis(genesis)
            state_store.save(state)
            block_store = BlockStore(MemKV())
            evpool = EvidencePool(MemKV(), state_store, block_store)
            mempool = TxMempool(client, MempoolConfig())
            block_exec = BlockExecutor(
                state_store,
                client,
                mempool,
                block_store=block_store,
                evidence_pool=evpool,
            )
            timeline = TimelineRecorder(capacity=4096)
            node = ModelNode(
                index=i,
                moniker=moniker,
                priv=priv,
                cs=None,  # type: ignore[arg-type]  # set just below
                evpool=evpool,
                block_store=block_store,
                state_store=state_store,
                timeline=timeline,
            )
            pv = MemoPV(priv, node._vote_time, self.sign_memos[i])
            cs = ConsensusState(
                _mc_consensus_config(),
                state,
                block_exec,
                block_store,
                privval=pv,
                evidence_pool=evpool,
                timeline=timeline,
            )
            node.cs = cs
            # the start() work the model does synchronously: pubkey
            # fetch, ticker swap, round-0 schedule — no services run
            cs.privval_pub_key = priv.pub_key()
            cs.ticker = _StubTicker(node)
            cs._vote_time = node._vote_time
            self._spy_evpool(node)
            self._spy_proposals(block_exec)
            self.nodes.append(node)
            self._by_moniker[moniker] = node

        for node in self.nodes:
            reactor = _ModelReactor(self, node)
            node.byz_harness = byzantine.maybe_install(
                node.cs, reactor, node.moniker
            )
            node.cs._schedule_round_0()

    def _spy_evpool(self, node: ModelNode) -> None:
        orig = node.evpool.report_conflicting_votes

        def spy(vote_a, vote_b, _node=node, _orig=orig):
            _node.detections.append(
                (
                    vote_a.height,
                    _h8(vote_a.validator_address),
                    _node.block_store.height(),
                )
            )
            return _orig(vote_a, vote_b)

        node.evpool.report_conflicting_votes = spy  # type: ignore[assignment]

    def _spy_proposals(self, block_exec: BlockExecutor) -> None:
        orig = block_exec.create_proposal_block

        def spy(height, state, commit, addr, _orig=orig):
            block, parts = _orig(height, state, commit, addr)
            self.proposed.add(block.hash())
            return block, parts

        block_exec.create_proposal_block = spy  # type: ignore[assignment]

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._byz_stack.close()
        byzantine.reset()

    # -- message plumbing ---------------------------------------------

    @staticmethod
    def key_for(msg) -> Optional[Tuple]:
        if isinstance(msg, VoteMessage):
            v = msg.vote
            return (
                "v",
                v.height,
                v.round,
                v.type,
                v.validator_index,
                _h8(v.block_id.hash),
            )
        if isinstance(msg, ProposalMessage):
            p = msg.proposal
            return ("p", p.height, p.round, p.pol_round, _h8(p.block_id.hash))
        if isinstance(msg, BlockPartMessage):
            root = msg.part.proof.compute_root_hash()
            return ("b", msg.height, msg.round, msg.part.index, _h8(root))
        return None

    def _enqueue_for(self, moniker: str, msg) -> None:
        key = self.key_for(msg)
        if key is None:
            return
        self._by_moniker[moniker].pending.setdefault(key, msg)

    def _broadcast(self, src: ModelNode, msg) -> None:
        key = self.key_for(msg)
        if key is None:
            return
        for node in self.nodes:
            if node is not src:
                node.pending.setdefault(key, msg)

    async def _drain_internal(self, node: ModelNode) -> None:
        """Process the node's own outputs synchronously, broadcasting
        each to peers first (mirrors the receive-loop's internal-first
        priority without running the loop)."""
        q = node.cs.internal_msg_queue
        while not q.empty():
            mi = q.get_nowait()
            self._broadcast(node, mi.msg)
            await node.cs._handle_msg(mi)

    # -- enabledness --------------------------------------------------

    def _deliverable(self, node: ModelNode, key: Tuple) -> bool:
        rs = node.cs.rs
        kind = key[0]
        if kind == "v":
            # exact-height only; the late-precommit catchup path
            # (vote.height+1 == rs.height) is reached via held votes
            # delivered before the receiver advanced
            return key[1] == rs.height
        if kind == "p":
            return (
                key[1] == rs.height
                and key[2] == rs.round
                and rs.proposal is None
            )
        if kind == "b":
            if key[1] != rs.height:
                return False
            ps = rs.proposal_block_parts
            if ps is None:
                return False  # held until the proposal header lands
            return (
                _h8(ps.header().hash) == key[4]
                and key[3] < ps.total
                and ps.get_part(key[3]) is None
            )
        return False

    def _purge(self) -> None:
        """Drop pending messages and timeouts the receiver can never
        use again. Run after every transition so equal states have
        equal pending sets (the fingerprint covers them)."""
        for node in self.nodes:
            rs = node.cs.rs
            dead = []
            for key in node.pending:
                kind = key[0]
                if kind == "v":
                    if key[1] < rs.height:
                        dead.append(key)
                elif kind == "p":
                    if key[1] < rs.height or (
                        key[1] == rs.height
                        and (
                            key[2] < rs.round
                            or (key[2] == rs.round and rs.proposal is not None)
                        )
                    ):
                        dead.append(key)
                elif kind == "b":
                    if key[1] < rs.height:
                        dead.append(key)
                    else:
                        ps = rs.proposal_block_parts
                        if (
                            key[1] == rs.height
                            and ps is not None
                            and _h8(ps.header().hash) == key[4]
                            and key[3] < ps.total
                            and ps.get_part(key[3]) is not None
                        ):
                            dead.append(key)
            for key in dead:
                del node.pending[key]
            ti = node.pending_timeout
            if ti is not None and (
                ti.height != rs.height
                or ti.round < rs.round
                or (ti.round == rs.round and ti.step < rs.step)
            ):
                # _handle_timeout would ignore it (state.py stale guard)
                node.pending_timeout = None

    def transitions(self) -> List[Tuple]:
        """Enabled transitions: ("t", node_idx) fires the pending
        timeout, ("d", node_idx, key) delivers one pending message.
        Also refreshes pruning counters (round cap, finished nodes)."""
        self.pruned_round_cap = 0
        self.suppressed_done = 0
        out: List[Tuple] = []
        for node in self.nodes:
            node_trans: List[Tuple] = []
            ti = node.pending_timeout
            if ti is not None:
                if (
                    ti.step == RoundStep.PRECOMMIT_WAIT
                    and ti.round >= self.cfg.max_round
                ):
                    # round horizon: never advance past max_round
                    self.pruned_round_cap += 1
                else:
                    node_trans.append(("t", node.index))
            for key in sorted(node.pending):
                if self._deliverable(node, key):
                    node_trans.append(("d", node.index, key))
            if node.done(self.cfg.target_height):
                # finished nodes stop acting; their already-broadcast
                # messages stay deliverable at laggards
                self.suppressed_done += len(node_trans)
            else:
                out.extend(node_trans)
        return out

    def all_done(self) -> bool:
        return all(n.done(self.cfg.target_height) for n in self.nodes)

    # -- execution ----------------------------------------------------

    def apply(self, t: Tuple) -> None:
        self.loop.run_until_complete(self._apply_async(t))

    async def _apply_async(self, t: Tuple) -> None:
        node = self.nodes[t[1]]
        if t[0] == "t":
            ti = node.pending_timeout
            if ti is None:
                raise RuntimeError(f"timeout transition not enabled: {t}")
            node.pending_timeout = None
            await node.cs._handle_timeout(ti)
        else:
            msg = node.pending.pop(t[2], None)
            if msg is None or not self._deliverable_key_ok(node, t[2], msg):
                raise RuntimeError(f"deliver transition not enabled: {t}")
            await node.cs._handle_msg(MsgInfo(msg=msg, peer_id="mc-net"))
        await self._drain_internal(node)
        self._purge()

    def _deliverable_key_ok(self, node: ModelNode, key: Tuple, msg) -> bool:
        # re-add so _deliverable sees a consistent view, then remove
        node.pending[key] = msg
        ok = self._deliverable(node, key)
        del node.pending[key]
        return ok

    # -- fingerprint ---------------------------------------------------

    def fingerprint(self) -> bytes:
        acc: List[Tuple] = []
        for node in self.nodes:
            rs = node.cs.rs
            votes_fp: List[Tuple] = []
            if rs.votes is not None:
                for r in sorted(rs.votes._round_vote_sets):
                    pv, pc = rs.votes._round_vote_sets[r]
                    votes_fp.append(
                        (
                            r,
                            tuple(
                                sorted(
                                    (v.validator_index, _h8(v.block_id.hash))
                                    for v in pv.list_votes()
                                )
                            ),
                            tuple(
                                sorted(
                                    (v.validator_index, _h8(v.block_id.hash))
                                    for v in pc.list_votes()
                                )
                            ),
                        )
                    )
            lc = rs.last_commit
            lc_fp = (
                tuple(sorted(v.validator_index for v in lc.list_votes()))
                if lc is not None
                else ()
            )
            chain = []
            for h in range(1, node.block_store.height() + 1):
                meta = node.block_store.load_block_meta(h)
                chain.append(_h8(meta.block_id.hash) if meta else "gone")
            ps = rs.proposal_block_parts
            ps_fp = (
                (
                    _h8(ps.header().hash),
                    sum(
                        1 << i
                        for i, part in enumerate(ps.parts)
                        if part is not None
                    ),
                )
                if ps is not None
                else None
            )
            prop = rs.proposal
            prop_fp = (
                (prop.height, prop.round, prop.pol_round, _h8(prop.block_id.hash))
                if prop is not None
                else None
            )
            ti = node.pending_timeout
            harness = node.byz_harness
            acc.append(
                (
                    rs.height,
                    rs.round,
                    rs.step,
                    prop_fp,
                    ps_fp,
                    _h8(rs.proposal_block.hash())
                    if rs.proposal_block is not None
                    else None,
                    (
                        rs.locked_round,
                        _h8(rs.locked_block.hash())
                        if rs.locked_block is not None
                        else None,
                    ),
                    (
                        rs.valid_round,
                        _h8(rs.valid_block.hash())
                        if rs.valid_block is not None
                        else None,
                    ),
                    rs.triggered_timeout_precommit,
                    tuple(votes_fp),
                    lc_fp,
                    tuple(chain),
                    _h8(node.cs.state.app_hash),
                    tuple(sorted(_h8(ev.hash()) for ev in node.evpool._pending)),
                    tuple(
                        (va.height, _h8(va.validator_address))
                        for va, _vb in node.evpool._consensus_buffer
                    ),
                    tuple(sorted(node.pending)),
                    (ti.height, ti.round, ti.step) if ti is not None else None,
                    node.clock_ns,
                    tuple(harness.fired) if harness is not None else (),
                    tuple(node.detections),
                )
            )
        return hashlib.sha1(repr(acc).encode()).digest()
