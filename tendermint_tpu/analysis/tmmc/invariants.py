"""Machine-checked consensus invariants, evaluated at EVERY explored
state.

Each checker returns a list of human-readable messages; ``check_all``
tags them with the gate rule name. The gate anchors its
tmlint.Violation at the ``def`` line of the failed checker, so the
suppression form ``# tmmc: mc-ok`` (or ``# tmmc: mc-ok=<rule>``) on
that line is what the lint substrate scans.

| rule              | property                                        |
|-------------------|-------------------------------------------------|
| mc-agreement      | no two nodes commit different block IDs at a    |
|                   | height                                          |
| mc-validity       | every committed block was produced by an honest |
|                   | proposer (never the byzantine EVIL block)       |
| mc-accountability | every *detected* equivocation has pending or    |
|                   | committed DuplicateVoteEvidence once the        |
|                   | detecting node's pool has run an update         |
| mc-stall          | some transition is enabled while any node is    |
|                   | below the target height (modulo the round cap)  |

Accountability deliberately conditions on DETECTION, not on the
adversary having fired: an evil vote delivered after its victim moved
past the height is silently dropped by the real implementation (no
conflict is ever observed), which is correct behavior, not an
accountability failure. The harness records detections by spying on
``evpool.report_conflicting_votes``; once the detecting node's store
advances past the detection point (so ``EvidencePool.update`` has
processed the consensus buffer), matching evidence must exist.
"""

from __future__ import annotations

from typing import List, Tuple

from ...consensus.byzantine import EVIL_BLOCK_ID


def check_agreement(net) -> List[str]:
    out: List[str] = []
    by_height = {}
    for node in net.nodes:
        for h in range(1, node.block_store.height() + 1):
            meta = node.block_store.load_block_meta(h)
            if meta is None:
                continue
            first = by_height.setdefault(
                h, (node.moniker, meta.block_id.hash)
            )
            if first[1] != meta.block_id.hash:
                out.append(
                    f"height {h}: {first[0]} committed "
                    f"{first[1].hex()[:12]} but {node.moniker} committed "
                    f"{meta.block_id.hash.hex()[:12]}"
                )
    return out


def check_validity(net) -> List[str]:
    out: List[str] = []
    for node in net.nodes:
        for h in range(1, node.block_store.height() + 1):
            meta = node.block_store.load_block_meta(h)
            if meta is None:
                continue
            bh = meta.block_id.hash
            if bh == EVIL_BLOCK_ID.hash:
                out.append(
                    f"{node.moniker} committed the byzantine EVIL block "
                    f"at height {h}"
                )
            elif bh not in net.proposed:
                out.append(
                    f"{node.moniker} committed {bh.hex()[:12]} at height "
                    f"{h} which no honest proposer produced"
                )
    return out


def check_accountability(net) -> List[str]:
    out: List[str] = []
    for node in net.nodes:
        for eq_height, addr_tag, store_at_detect in node.detections:
            if node.block_store.height() <= store_at_detect:
                # no EvidencePool.update has run since the detection;
                # the double-sign is still in the consensus buffer
                continue
            if _has_matching_evidence(node, eq_height, addr_tag):
                continue
            out.append(
                f"{node.moniker} detected equivocation by {addr_tag} at "
                f"height {eq_height} (store height {store_at_detect}) but "
                f"holds no pending or committed DuplicateVoteEvidence at "
                f"store height {node.block_store.height()}"
            )
    return out


def _has_matching_evidence(node, eq_height: int, addr_tag: str) -> bool:
    def _matches(ev) -> bool:
        vote_a = getattr(ev, "vote_a", None)
        return (
            vote_a is not None
            and vote_a.height == eq_height
            and vote_a.validator_address.hex()[:12] == addr_tag
        )

    if any(_matches(ev) for ev in node.evpool._pending):
        return True
    for h in range(1, node.block_store.height() + 1):
        block = node.block_store.load_block(h)
        if block is not None and any(_matches(ev) for ev in block.evidence):
            return True
    return False


def check_stall(net, enabled) -> List[str]:
    if net.all_done():
        return []
    if enabled:
        return []
    if net.pruned_round_cap > 0 or net.suppressed_done > 0:
        # progress exists beyond the exploration horizon (a capped
        # round advance, or a finished node's suppressed actions) —
        # the model cut it, the protocol didn't stall
        return []
    lagging = [
        f"{n.moniker}@h{n.cs.rs.height}r{n.cs.rs.round}s{n.cs.rs.step}"
        for n in net.nodes
        if not n.done(net.cfg.target_height)
    ]
    return [
        "no transition enabled while nodes are below target height "
        f"{net.cfg.target_height}: {', '.join(lagging)}"
    ]


def check_all(net, enabled) -> List[Tuple[str, str]]:
    out: List[Tuple[str, str]] = []
    for msg in check_agreement(net):
        out.append(("mc-agreement", msg))
    for msg in check_validity(net):
        out.append(("mc-validity", msg))
    for msg in check_accountability(net):
        out.append(("mc-accountability", msg))
    for msg in check_stall(net, enabled):
        out.append(("mc-stall", msg))
    return out
