"""lockwatch — runtime lock-order observer (the `-race` analog tmlint
can't be).

Static rules can prove a mutation is inside *a* lock; they cannot
prove two threads take two locks in a consistent *order*. The
reference solves this with Go's race detector plus a hand-maintained
lockrank table; this module is the same idea sized for this codebase:

- every watched lock acquisition records an edge `held -> acquiring`
  in a process-global directed graph, keyed by lock *name* (creation
  site), with the first witnessing thread and location kept for the
  report;
- `cycles()` finds ordering cycles in that graph — a witnessed
  A->B edge in one thread plus B->A in another is a latent deadlock
  even if the run happened not to interleave them fatally;
- `RANK` is the declared order (Go-lockrank style) for the crypto
  path's named locks; `order_violations()` reports witnessed edges
  that contradict it;
- holds longer than the fast-path budget (`TM_TPU_LOCKWATCH_BUDGET_S`,
  default 0.25 s) are recorded — consensus must never park behind a
  slow device interaction holding a shared lock.

Instrumentation has two halves, because locks are born two ways:

- `instrument_creation(module)` swaps the module's `threading`
  reference for a proxy whose Lock()/RLock() return watched locks —
  catches locks created *during* the test (e.g. per-CircuitBreaker
  instance locks, rebuilt every test by the breaker-reset fixture);
- `instrument_attr(module, attr, name)` wraps a module-level lock
  that already exists at import time (sigcache._lock,
  tpu_verifier._wedged_lock, breaker._REG_LOCK).

`enable()` applies both to the known crypto-path modules and
`disable()` restores them, returning a `Report`. tests/conftest.py
turns this on (autouse) for the chaos/fault/fuzz suites and asserts
zero cycles and zero rank violations at teardown.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

__all__ = [
    "LockWatch",
    "Report",
    "RANK",
    "HOLD_LOG",
    "HOLD_LOG_CAP",
    "enable",
    "disable",
    "active",
    "instrument_creation",
    "instrument_attr",
]

DEFAULT_HOLD_BUDGET_S = 0.25

# Process-wide structured record of every hold-budget overrun ever
# witnessed (across watch windows): the runtime half of tmlive's
# block-under-lock cross-check — tests/test_tmlive.py asserts every
# entry here is statically explained (a flagged/suppressed blocking
# site under that lock, or holdflow.OVERRUN_OK scheduler-noise
# rationale). Bounded at HOLD_LOG_CAP; overflow increments
# HOLD_LOG_DROPPED instead of growing (the cross-check needs lock
# NAMES, which repeat, not an unbounded event stream).
HOLD_LOG: List[dict] = []
HOLD_LOG_CAP = 256
HOLD_LOG_DROPPED = 0
# guards HOLD_LOG/HOLD_LOG_DROPPED: the log is cross-window global, so
# a per-watch lock would not serialize two concurrent watches
_hold_log_lock = threading.Lock()


def _hold_budget() -> float:
    try:
        return float(os.environ.get("TM_TPU_LOCKWATCH_BUDGET_S", ""))
    except ValueError:
        return DEFAULT_HOLD_BUDGET_S


# The declared acquisition order for the crypto path's named locks
# (lower rank first). Proven acyclic by running the chaos and fault
# suites under lockwatch; the witnessed edges are a subset of this
# partial order:
#
#   breaker.registry -> breaker.instance  (fresh() retires the old
#       instance's probe timer under _REG_LOCK)
#   breaker.registry -> metrics.metric    (CircuitBreaker.__init__
#       publishes its state gauge while breaker_for holds _REG_LOCK)
#   breaker.instance -> metrics.metric    (state transitions publish
#       gauges/counters under the instance lock)
#   sigcache.rotate  -> metrics.metric    (_rotate bumps the eviction
#       counter under the rotation lock)
#   trace.ring       -> metrics.metric    (span close feeds latency
#       histograms while appending to the ring)
#   tpu_verifier.wedged and metrics.* are leaves: nothing is acquired
#   while they are held.
RANK: Dict[str, int] = {
    "breaker.registry": 10,
    "breaker.instance": 20,
    "sigcache.rotate": 30,
    "trace.ring": 35,
    "tpu_verifier.wedged": 40,
    "metrics.metric": 50,
    "metrics.registry": 55,
}

# The expected edges of the partial order above, classified by how
# they are PROVEN. "static": tmrace's lock-order pass must derive the
# edge from source on every gate run — if the code stops producing it,
# the gate fails until this table is updated, so RANK can never
# silently drift from the code. "runtime-only": the edge exists only
# through dynamic dispatch the static call graph cannot resolve (say
# why); lockwatch still witnesses it at runtime.
RANK_EDGES: Dict[Tuple[str, str], str] = {
    # fresh() retires the old instance's probe timer under _REG_LOCK
    ("breaker.registry", "breaker.instance"): "static",
    # CircuitBreaker.__init__ publishes its state gauge while
    # breaker_for/fresh hold _REG_LOCK
    ("breaker.registry", "metrics.metric"): "static",
    # state transitions publish gauges/counters under the instance lock
    ("breaker.instance", "metrics.metric"): "static",
    # _rotate bumps the eviction counter under the rotation lock
    ("sigcache.rotate", "metrics.metric"): "static",
    # witnessed under the chaos suites when a span closes while a ring
    # maintenance call (set_capacity/reset/snapshot) holds the ring
    # lock on another thread's stack above a metric touch; the span
    # close itself observes its histogram BEFORE the lock-free ring
    # append, so no static path holds trace.ring across a metric
    # acquisition — lockwatch alone can prove this one
    ("trace.ring", "metrics.metric"): "runtime-only",
}


class Report:
    """Frozen result of one watch window."""

    def __init__(
        self,
        edges: Dict[Tuple[str, str], dict],
        long_holds: List[dict],
        acquisitions: int,
    ) -> None:
        self.edges = edges
        self.long_holds = long_holds
        self.acquisitions = acquisitions
        self.cycles = _find_cycles(set(edges))

    def order_violations(
        self, rank: Optional[Dict[str, int]] = None
    ) -> List[dict]:
        rank = RANK if rank is None else rank
        out = []
        for (a, b), info in sorted(self.edges.items()):
            ra, rb = rank.get(a), rank.get(b)
            if ra is not None and rb is not None and ra > rb:
                out.append({"edge": (a, b), "rank": (ra, rb), **info})
        return out

    def render(self) -> str:
        lines = [
            f"lockwatch: {self.acquisitions} acquisitions, "
            f"{len(self.edges)} distinct edges"
        ]
        for cyc in self.cycles:
            lines.append("  CYCLE: " + " -> ".join(cyc + [cyc[0]]))
        for v in self.order_violations():
            a, b = v["edge"]
            lines.append(
                f"  RANK VIOLATION: {a} (rank {v['rank'][0]}) held while "
                f"acquiring {b} (rank {v['rank'][1]}) at {v['where']}"
            )
        for h in self.long_holds:
            lines.append(
                f"  LONG HOLD: {h['name']} held {h['held_s']:.3f}s "
                f"(budget {h['budget_s']:.3f}s) by {h['thread']}"
            )
        return "\n".join(lines)


def _find_cycles(edges: Set[Tuple[str, str]]) -> List[List[str]]:
    """Simple cycles in the witnessed-order graph (includes self-loops:
    two distinct instances of the same lock class acquired nested is
    reported as name->name). Colored DFS; each cycle reported once."""
    graph: Dict[str, List[str]] = {}
    for a, b in sorted(edges):
        graph.setdefault(a, []).append(b)
    seen_cycles: List[List[str]] = []
    found: Set[Tuple[str, ...]] = set()

    def dfs(node: str, stack: List[str], on_stack: Set[str]) -> None:
        for nxt in graph.get(node, ()):
            if nxt in on_stack:
                cyc = stack[stack.index(nxt):]
                # canonical rotation so each cycle reports once
                i = cyc.index(min(cyc))
                canon = tuple(cyc[i:] + cyc[:i])
                if canon not in found:
                    found.add(canon)
                    seen_cycles.append(list(canon))
            else:
                on_stack.add(nxt)
                stack.append(nxt)
                dfs(nxt, stack, on_stack)
                stack.pop()
                on_stack.discard(nxt)

    for start in sorted(graph):
        dfs(start, [start], {start})
    return seen_cycles


class LockWatch:
    """The recording core. Thread-safe; all graph state behind one
    internal (unwatched) lock."""

    def __init__(self, hold_budget_s: Optional[float] = None) -> None:
        self.hold_budget_s = (
            _hold_budget() if hold_budget_s is None else hold_budget_s
        )
        self._mu = threading.Lock()
        self._tls = threading.local()
        self._edges: Dict[Tuple[str, str], dict] = {}
        self._long_holds: List[dict] = []
        self._acquisitions = 0

    # -- per-thread held stack --

    def _stack(self) -> List[list]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def on_acquired(self, name: str, where: str) -> None:
        st = self._stack()
        held = [h[0] for h in st]
        with self._mu:
            self._acquisitions += 1
            # a held->acquiring edge per lock currently held. h == name
            # is NOT skipped: RLock reentry is filtered by the caller,
            # so a same-name edge means two *instances* of one lock
            # class nested — a real instance-order hazard, reported as
            # a self-loop cycle.
            for h in held:
                edge = (h, name)
                if edge not in self._edges:
                    self._edges[edge] = {
                        "where": where,
                        "thread": threading.current_thread().name,
                    }
        st.append([name, time.monotonic(), where])

    def on_released(self, name: str) -> None:
        st = self._stack()
        # release is not always LIFO (Condition.wait releases from the
        # middle): pop the most recent entry with this name
        for i in range(len(st) - 1, -1, -1):
            if st[i][0] == name:
                _, t0, where = st.pop(i)
                held = time.monotonic() - t0
                if held > self.hold_budget_s:
                    record = {
                        "name": name,
                        "where": where,
                        "held_s": held,
                        "budget_s": self.hold_budget_s,
                        "thread": threading.current_thread().name,
                    }
                    with self._mu:
                        self._long_holds.append(record)
                    # process-global structured record for the tmlive
                    # cross-check (bounded; separate lock, never
                    # nested inside _mu). Only the process-ACTIVE
                    # watch feeds it: standalone unit-test watches
                    # with synthetic lock names must not demand
                    # OVERRUN_OK entries
                    if _ACTIVE is self:
                        global HOLD_LOG_DROPPED
                        with _hold_log_lock:
                            if len(HOLD_LOG) < HOLD_LOG_CAP:
                                HOLD_LOG.append(record)
                            else:
                                HOLD_LOG_DROPPED += 1
                return

    def report(self) -> Report:
        with self._mu:
            return Report(
                dict(self._edges),
                list(self._long_holds),
                self._acquisitions,
            )


class _WatchedLock:
    """Wraps one real lock. Proxies the full Lock/RLock surface so it
    can stand in anywhere (including inside threading.Condition);
    records only *successful* acquisitions. Recording routes through
    the process's ACTIVE watch when one exists, falling back to the
    bound one (direct unit-test use): a proxy-created lock that
    outlives its window (an object registered process-globally during
    a watched test) then reports into the next window instead of a
    dead report."""

    def __init__(self, watch: LockWatch, inner, name: str) -> None:
        self._watch = watch
        self._inner = inner
        self._name = name
        self._reentrant = hasattr(inner, "_is_owned") or type(
            inner
        ).__name__ in ("RLock", "_RLock")
        self._owner: Optional[int] = None
        self._depth = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        if self._reentrant and self._owner == me:
            ok = self._inner.acquire(blocking, timeout)
            if ok:
                self._depth += 1
            return ok
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._owner = me
            self._depth = 1
            where = _caller()
            (_ACTIVE or self._watch).on_acquired(self._name, where)
        return ok

    def release(self) -> None:
        if self._reentrant and self._depth > 1:
            self._depth -= 1
            self._inner.release()
            return
        self._owner = None
        self._depth = 0
        self._inner.release()
        # release may land in a different window than the acquire;
        # on_released pops by name and no-ops when it isn't found
        (_ACTIVE or self._watch).on_released(self._name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __getattr__(self, item):  # _at_fork_reinit, _is_owned, ...
        return getattr(self._inner, item)

    def __repr__(self) -> str:
        return f"<lockwatch {self._name} wrapping {self._inner!r}>"


def _caller() -> str:
    """file:line of the acquisition site outside this module."""
    f = sys._getframe(2)
    here = os.path.dirname(__file__)
    while f is not None and f.f_code.co_filename.startswith(here):
        f = f.f_back
    if f is None:  # pragma: no cover
        return "?"
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


class _ThreadingProxy:
    """Stands in for a module's `threading` global: Lock()/RLock()
    return watched locks named by a `namer` over the creating frame
    (one name per lock *class*, exactly how Go ranks lock classes,
    not instances); everything else delegates to real threading —
    Timer/Thread/Event keep their unwatched internals."""

    def __init__(
        self, watch: LockWatch, namer: Callable[..., str]
    ) -> None:
        self._watch = watch
        self._namer = namer

    def _name(self) -> str:
        f = sys._getframe(2)
        owner = type(f.f_locals.get("self", None)).__name__
        site = f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"
        return self._namer(owner, site)

    def Lock(self):
        return _WatchedLock(self._watch, threading.Lock(), self._name())

    def RLock(self):
        return _WatchedLock(self._watch, threading.RLock(), self._name())

    def __getattr__(self, item):
        return getattr(threading, item)


# -- module instrumentation -------------------------------------------------

_ACTIVE: Optional[LockWatch] = None
_UNDO: List[Callable[[], None]] = []
# guards _UNDO and enable/disable transitions (instrumentation is
# driven from the test main thread, but the lint tool holds itself to
# its own lock-global-mutation rule)
_undo_lock = threading.Lock()


def active() -> Optional[LockWatch]:
    return _ACTIVE


def instrument_creation(
    watch: LockWatch, module, namer: Optional[Callable[..., str]] = None
) -> None:
    """Future Lock()/RLock() calls inside `module` produce watched
    locks. `namer(owner_class_name, site)` maps a creation to its
    stable rank-table name; default names by creation site."""
    if getattr(module, "threading", None) is None:
        raise ValueError(f"{module.__name__} has no `threading` global")
    orig = module.threading
    module.threading = _ThreadingProxy(
        watch, namer or (lambda owner, site: site)
    )
    with _undo_lock:
        _UNDO.append(lambda: setattr(module, "threading", orig))


def instrument_attr(watch: LockWatch, obj, attr: str, name: str) -> None:
    """Wrap a lock that already exists as `obj.attr` (module-level
    locks, but also per-object locks born before the window — e.g.
    DEFAULT_REGISTRY's import-time metric instruments)."""
    inner = getattr(obj, attr)
    if isinstance(inner, _WatchedLock):  # already watched
        return
    setattr(obj, attr, _WatchedLock(watch, inner, name))
    with _undo_lock:
        _UNDO.append(lambda: setattr(obj, attr, inner))


def enable(hold_budget_s: Optional[float] = None) -> LockWatch:
    """Instrument the crypto-path modules and start recording. Import
    is deferred so `analysis` never drags the jax stack in."""
    global _ACTIVE
    if _ACTIVE is not None:
        return _ACTIVE
    watch = LockWatch(hold_budget_s)

    from ..crypto import breaker, sigcache, tpu_verifier
    from ..libs import metrics, trace

    # locks created during the watch window: per-CircuitBreaker
    # instance locks are rebuilt every test by the breaker-reset
    # fixture, per-Metric/Registry locks by any new registry. Named
    # by owning class, not creation line, so edits don't break ranks.
    instrument_creation(
        watch,
        breaker,
        namer=lambda owner, site: (
            "breaker.instance" if owner == "CircuitBreaker" else site
        ),
    )
    instrument_creation(
        watch,
        metrics,
        namer=lambda owner, site: (
            "metrics.registry" if owner == "Registry" else "metrics.metric"
        ),
    )
    # module-level locks that already exist at import time
    instrument_attr(watch, breaker, "_REG_LOCK", "breaker.registry")
    instrument_attr(watch, sigcache, "_lock", "sigcache.rotate")
    instrument_attr(watch, tpu_verifier, "_wedged_lock", "tpu_verifier.wedged")
    instrument_attr(watch, trace, "_ring_lock", "trace.ring")
    # DEFAULT_REGISTRY's instruments (breaker gauges, sigcache/tpu
    # counters) were created at import, long before any window — wrap
    # their per-metric locks in place so the RANK-documented
    # *->metrics.metric edges are actually witnessed, not assumed
    instrument_attr(watch, metrics.DEFAULT_REGISTRY, "_lock", "metrics.registry")
    for m in list(metrics.DEFAULT_REGISTRY._metrics.values()):
        instrument_attr(watch, m, "_lock", "metrics.metric")

    _ACTIVE = watch
    return watch


def disable() -> Report:
    """Restore every instrumented module and return the report."""
    global _ACTIVE
    watch = _ACTIVE
    _ACTIVE = None
    while True:
        with _undo_lock:
            if not _UNDO:
                break
            undo = _UNDO.pop()
        undo()
    if watch is None:
        return Report({}, [], 0)
    return watch.report()
