"""Serving-root discovery for tmcost.

A *serving root* is a function whose invocation count is controlled by
the outside world — one call per client request, per peer message, or
per committed block. The cost gate's contract is per-request: every
root gets a symbolic cost class checked against the reviewed budget
table `cost_budgets.json`, and a root missing from the table is red
(a new route cannot ship unbudgeted).

Three families, the first two machine-derived the same way tmsafe
derives its entries (so the catalog cannot rot by hand):

1. **RPC route handlers** — every function in the package with an
   `RPCRequest`-annotated parameter (the JSON-RPC routes in
   rpc/core.py). One call per client HTTP/WS request.
2. **P2P recv handlers** — every function with an `Envelope`-annotated
   parameter plus the inline `async for envelope in <channel>` receive
   loops (the evidence/mempool/pex reactor shape — same discovery as
   tmsafe's validate pass). One call per peer message; the envelope
   loop itself is the per-request boundary, not a cost factor.
3. **Per-block consensus entry points** — a small REVIEWED catalog
   (`CONSENSUS_ROOTS`): the functions the node pays once per block
   regardless of traffic. Their budgets pin the committee-size trade
   the paper centers on (EdDSA vs BLS, arxiv 2302.00418: commit
   verification cost as a function of committee size).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from ..tmcheck.callgraph import FuncInfo, Package, _body_walk
from ..tmsafe.sources import _annotated_params

__all__ = ["Root", "CONSENSUS_ROOTS", "discover_roots", "root_id"]

FuncKey = Tuple[str, str]

# the per-block entry points: (path, qualname) -> why it is a root.
# Every key must resolve in the call graph (pinned by test); adding an
# entry here is a reviewed change, exactly like tmsafe's MUTATION_SINKS.
CONSENSUS_ROOTS: Dict[FuncKey, str] = {
    ("types/validation.py", "verify_commit"): (
        "full commit verification — paid once per block by every full "
        "node; the committee-size cost the paper trades against"
    ),
    ("types/validation.py", "verify_commit_light"): (
        "light commit verification — blocksync/light-client per-header "
        "cost"
    ),
    ("types/validation.py", "verify_commit_light_bulk"): (
        "bulk light verification — the stateless fleet-serving path"
    ),
    ("state/execution.py", "BlockExecutor.apply_block"): (
        "block execution + store writes — the per-commit critical path"
    ),
}


class Root:
    """One serving root: identity, family, tainted params."""

    __slots__ = ("key", "family", "attacker_params", "why")

    def __init__(
        self,
        key: FuncKey,
        family: str,
        attacker_params: Tuple[str, ...] = (),
        why: str = "",
    ) -> None:
        self.key = key
        self.family = family  # "rpc" | "p2p" | "consensus"
        self.attacker_params = attacker_params
        self.why = why

    def render(self) -> str:
        return f"{root_id(self.key)} [{self.family}]"


def root_id(key: FuncKey) -> str:
    """The budget-table identity of a root: 'path:qualname'."""
    return f"{key[0]}:{key[1]}"


def _has_envelope_loop(fi: FuncInfo) -> bool:
    """Same shape test as tmsafe.validate: `async for envelope in ...`
    marks an inline receive loop."""
    for node in _body_walk(fi.node):
        if (
            isinstance(node, ast.AsyncFor)
            and isinstance(node.target, ast.Name)
            and node.target.id == "envelope"
        ):
            return True
    return False


def discover_roots(pkg: Package) -> List[Root]:
    roots: Dict[FuncKey, Root] = {}
    for key, fi in sorted(pkg.functions.items()):
        if fi.path == "p2p/channel.py":
            # the Channel is the typed pipe itself — its send/deliver
            # methods take Envelope params but are plumbing, not
            # handlers; the handler side is where per-request work
            # begins
            continue
        rpc_params = _annotated_params(fi, "RPCRequest")
        if rpc_params:
            roots[key] = Root(key, "rpc", tuple(rpc_params))
            continue
        env_params = _annotated_params(fi, "Envelope")
        if env_params:
            roots[key] = Root(key, "p2p", tuple(env_params))
            continue
        if _has_envelope_loop(fi):
            # the loop target "envelope" is the attacker-controlled
            # value; the loop itself is the per-request boundary
            roots[key] = Root(key, "p2p", ("envelope",))
    for key, why in CONSENSUS_ROOTS.items():
        if key in pkg.functions:
            roots[key] = Root(key, "consensus", (), why)
    return [roots[k] for k in sorted(roots)]
