"""Loop-bound provenance dataflow: the per-request cost engine.

Every loop and comprehension bound in the serving region is classified
into a provenance lattice — not "how big" but "who controls it":

- ``const``    — literal / SCREAMING config constant / fixed container
- ``clamped``  — explicitly bounded by a config clamp: ``min(n, MAX_*)``,
  ``items[:CAP]``, ``range(min(...))`` (tmsafe amplify's recognizers,
  widened to any SCREAMING-name slice bound)
- ``lin``      — an unknown in-process collection (peers, sinks,
  subscriptions): linear in node-local state
- ``vset``     — validator-set-size-proportional (validators,
  signatures, powers — the committee-size axis of arxiv 2302.00418)
- ``block``    — block-content-proportional (txs, parts, evidence,
  events)
- ``store``    — store-height-range-proportional (``height() - base()``
  walks: grows without bound over the chain's life)
- ``attacker`` — derived from request params / peer message fields with
  no clamp between parse and use (the tmsafe VAL class, seen from the
  cost side)

The interprocedural half is the tmsafe shape: a monotone fixpoint over
the PR-5 call graph with one joined context per function. Each
function's **cost summary** is a set of *terms* — multisets of bound
classes, e.g. ``('vset',)`` for verify_commit's tally loop or
``('clamped', 'block')`` for a capped page of per-block work — and a
call site folds the callee's terms into the caller under the caller's
enclosing loop context, so a per-validator helper called inside a
per-part loop correctly costs ``block*vset``. Program-order walk, no
operand short-circuit (the PR-8/PR-10 vacuous-clean lesson, re-pinned
by tests/test_tmcost.py).

Three rules fire during the walk:

- ``cost-superlinear`` — a term acquires its second KNOWN-unbounded
  (``vset``-or-worse) factor: nested unbounded iteration per request.
  One clamp is enough (``clamped`` factors never count), same calculus
  as tmsafe's amplification rule but over OUR bounds, not just
  attacker taint; ``lin`` factors stay visible in budget terms (drift
  guards them) without firing the rule.
- ``cost-recompute`` — a known-expensive pure call (``to_proto`` /
  ``hash`` / merkle-tree construction; the EXPENSIVE catalogs) on a
  *stable* input — a value derived from a block/state-store load, i.e.
  per-block-immutable content whose encoding is recomputed per
  request. Functions living in a recognized serving-cache module
  (CACHE_MODULE_NAMES) are exempt: their miss path IS the one place
  that work belongs.
- ``cost-unclamped-alloc`` — ``bytes(n)``/``bytearray(n)``/sequence
  repetition sized by a ``store``-or-worse bound with no clamp.

Stability is a second boolean dataflow riding the same fixpoint:
born at ``*store.load_*`` calls, propagated through attributes,
subscripts, container accumulation, constructor wrapping, and callee
RETURN summaries (``_light_block_at`` returns stable because its body
assembles store loads). It deliberately does NOT propagate through
parameters: a context-insensitive param join marks a value stable for
EVERY caller once ANY caller passes store content (evidence objects
are store-derived in the block path but request content in the RPC
path), and that contamination produced six false recompute findings
on the first development run. The cost is an under-approximation —
a handler that loads a block and hands it to a helper for encoding is
seen only if the helper's own body touches the store — documented
here and in docs/static_analysis.md.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ..tmcheck.callgraph import CallSite, FuncInfo, Package
from ..tmsafe import amplify
from .roots import Root

__all__ = [
    "CONST",
    "CLAMPED",
    "LIN",
    "VSET",
    "BLOCK",
    "STORE",
    "ATTACKER",
    "CLASS_NAMES",
    "CostEngine",
    "Finding",
    "render_term",
]

FuncKey = Tuple[str, str]

CONST = 0
CLAMPED = 1
LIN = 2
VSET = 3
BLOCK = 4
STORE = 5
ATTACKER = 6

CLASS_NAMES = [
    "const", "clamped", "lin", "vset", "block", "store", "attacker",
]

# attribute/name markers for protocol-shaped collections. Reviewed:
# widening a marker set changes what the whole gate sees.
VSET_MARKERS = frozenset({
    "validators", "signatures", "powers", "pub_keys", "pubkeys",
    "voting_powers", "precommits_list",
})
BLOCK_MARKERS = frozenset({
    "txs", "parts", "evidence", "events", "deliver_tx_objs",
    "tx_results", "leaves", "chunks",
})

# known-expensive pure methods: receiver content fully determines the
# result, and the work is proportional to the receiver's size
EXPENSIVE_ATTRS = frozenset({
    "to_proto", "to_proto_bytes", "hash_bytes", "sign_bytes", "hash",
})
# known-expensive in-package functions (path, qualname): merkle tree /
# page assembly — the stateless-serving constructors
EXPENSIVE_TARGETS = frozenset({
    ("crypto/merkle.py", "MerkleMultiTree.__init__"),
    ("crypto/merkle.py", "MerkleMultiTree.from_byte_slices"),
    ("crypto/merkle.py", "multiproofs_from_byte_slices"),
    ("crypto/merkle.py", "proofs_from_byte_slices"),
    ("crypto/merkle.py", "hash_from_byte_slices"),
    ("types/tx.py", "txs_hash"),
    ("types/tx.py", "txs_proofs"),
})

# modules whose functions ARE the sanctioned memo layer: expensive
# calls inside them are the cache's miss path, not a recompute.
# Matched by basename so fixture packages can model the shape.
CACHE_MODULE_NAMES = frozenset({"servingcache.py"})

_STORE_LOAD_PREFIXES = ("load_",)
_MAX_FACTORS = 4
_MAX_TERMS = 12


def _is_screaming(name: str) -> bool:
    return bool(name) and name.isupper() and len(name) > 1


def _is_store_recv(node: ast.AST) -> bool:
    """`self.block_store`, `env.state_store`, bare `store` — the
    receiver shape of a store load/height call."""
    name = ""
    if isinstance(node, ast.Name):
        name = node.id
    elif isinstance(node, ast.Attribute):
        name = node.attr
    return "store" in name


def _iter_clamped(iter_node: ast.AST) -> bool:
    """tmsafe's clamp recognizers plus: a slice bounded by ANY
    SCREAMING name (`[:_RECENT_SNAPSHOTS]` is a config clamp even
    without a MAX_/LIMIT/CAP marker)."""
    if amplify.iter_clamped(iter_node):
        return True
    for node in ast.walk(iter_node):
        if isinstance(node, ast.Slice) and node.upper is not None:
            up = node.upper
            upname = ""
            if isinstance(up, ast.Name):
                upname = up.id
            elif isinstance(up, ast.Attribute):
                upname = up.attr
            if _is_screaming(upname):
                return True
    return False


def render_term(term: Tuple[int, ...]) -> str:
    return "*".join(CLASS_NAMES[c] for c in term)


def _lin_count(term: Tuple[int, ...]) -> int:
    """Factors of KNOWN-unbounded provenance (vset and up). A `lin`
    factor — an unknown node-local collection — participates in the
    budget terms (drift still guards it) but does not fire the
    superlinear rule: counting every label-tuple or key-type-group
    micro-iteration as a potential quadratic drowned the signal in 50+
    benign findings on the first development run."""
    return sum(1 for c in term if c >= VSET)


def _mk_term(factors: List[int]) -> Tuple[int, ...]:
    fs = sorted((c for c in factors if c >= CLAMPED), reverse=True)
    return tuple(fs[:_MAX_FACTORS])


def _cap_terms(terms: Set[Tuple[int, ...]]) -> Set[Tuple[int, ...]]:
    if len(terms) <= _MAX_TERMS:
        return terms
    ranked = sorted(
        terms, key=lambda t: (_lin_count(t), sum(t), t), reverse=True
    )
    return set(ranked[:_MAX_TERMS])


class Finding:
    __slots__ = ("rule", "path", "lineno", "col", "detail", "key")

    def __init__(self, rule, path, lineno, col, detail, key):
        self.rule = rule
        self.path = path
        self.lineno = lineno
        self.col = col
        self.detail = detail
        self.key = key


class _FnState:
    __slots__ = (
        "param_class",
        "ret_class",
        "ret_stable",
        "terms",
        "analyzed",
        "is_p2p_root",
    )

    def __init__(self) -> None:
        self.param_class: Dict[str, int] = {}
        self.ret_class: int = CONST
        self.ret_stable: bool = False
        self.terms: Set[Tuple[int, ...]] = set()
        self.analyzed = False
        self.is_p2p_root = False


class CostEngine:
    """Monotone fixpoint over the call graph; findings + per-function
    cost summaries (the root summaries feed the budget gate)."""

    def __init__(self, pkg: Package, roots: List[Root]) -> None:
        self.pkg = pkg
        self.roots = roots
        self.states: Dict[FuncKey, _FnState] = {}
        self.callers: Dict[FuncKey, Set[FuncKey]] = {}
        self.parent: Dict[FuncKey, Tuple[FuncKey, int]] = {}
        self.findings: Dict[Tuple[str, str, int, int], Finding] = {}
        self._work: List[FuncKey] = []
        self._queued: Set[FuncKey] = set()

    # -- public --

    def run(self) -> List[Finding]:
        for r in self.roots:
            if r.key not in self.pkg.functions:
                continue
            st = self._state(r.key)
            if r.family == "p2p":
                st.is_p2p_root = True
            for p in r.attacker_params:
                st.param_class[p] = max(
                    st.param_class.get(p, CONST), ATTACKER
                )
            self._enqueue(r.key)
        while self._work:
            key = self._work.pop()
            self._queued.discard(key)
            self._analyze(key)
        return sorted(
            self.findings.values(),
            key=lambda f: (f.path, f.lineno, f.col, f.rule),
        )

    def cost_of(self, key: FuncKey) -> List[str]:
        """Canonical rendered cost of a function: its term strings,
        sorted; ['const'] when no non-const work was found."""
        st = self.states.get(key)
        if st is None or not st.terms:
            return ["const"]
        return sorted(render_term(t) for t in st.terms)

    def chain(self, key: FuncKey) -> List[str]:
        seen: Set[FuncKey] = set()
        chain: List[str] = []
        cur: Optional[FuncKey] = key
        while cur is not None and cur not in seen:
            seen.add(cur)
            fi = self.pkg.functions.get(cur)
            chain.append(fi.render() if fi else f"{cur[0]}:{cur[1]}")
            nxt = self.parent.get(cur)
            cur = nxt[0] if nxt else None
        chain.reverse()
        return chain

    # -- machinery --

    def _state(self, key: FuncKey) -> _FnState:
        st = self.states.get(key)
        if st is None:
            st = _FnState()
            self.states[key] = st
        return st

    def _enqueue(self, key: FuncKey) -> None:
        if key not in self._queued:
            self._queued.add(key)
            self._work.append(key)

    def _flow_into(
        self,
        caller: FuncKey,
        callee: FuncKey,
        classes: Dict[str, int],
        lineno: int,
    ) -> "_FnState":
        st = self._state(callee)
        grew = False
        for name, cls in classes.items():
            if cls > st.param_class.get(name, CONST):
                st.param_class[name] = cls
                grew = True
        if grew or not st.analyzed:
            self.parent.setdefault(callee, (caller, lineno))
            self._enqueue(callee)
        self.callers.setdefault(callee, set()).add(caller)
        return st

    def _summary_update(
        self,
        key: FuncKey,
        ret_class: int,
        ret_stable: bool,
        terms: Set[Tuple[int, ...]],
    ) -> None:
        st = self._state(key)
        grew = False
        if ret_class > st.ret_class:
            st.ret_class = ret_class
            grew = True
        if ret_stable and not st.ret_stable:
            st.ret_stable = True
            grew = True
        new_terms = _cap_terms(st.terms | terms)
        if new_terms != st.terms:
            st.terms = new_terms
            grew = True
        if grew:
            for c in self.callers.get(key, ()):
                self._enqueue(c)

    def report(self, rule, key, node, detail) -> None:
        fi = self.pkg.functions[key]
        k = (rule, fi.path, node.lineno, node.col_offset)
        if k not in self.findings:
            self.findings[k] = Finding(
                rule, fi.path, node.lineno, node.col_offset, detail, key
            )

    def _analyze(self, key: FuncKey) -> None:
        fi = self.pkg.functions.get(key)
        if fi is None:
            return
        st = self._state(key)
        st.analyzed = True
        walker = _CostWalker(self, fi, st)
        walker.run()
        self._summary_update(
            key, walker.ret_class, walker.ret_stable, walker.terms
        )


class _CostWalker:
    """One function body, statements in program order, operands always
    evaluated (never short-circuited)."""

    def __init__(self, eng: CostEngine, fi: FuncInfo, st: _FnState) -> None:
        self.eng = eng
        self.fi = fi
        self.key = fi.key
        self.in_cache_module = (
            fi.path.rsplit("/", 1)[-1] in CACHE_MODULE_NAMES
        )
        self.is_p2p_root = st.is_p2p_root
        # name -> (bound class, locally-store-derived). Stability never
        # enters through parameters (module docstring: the cross-caller
        # contamination class)
        self.env: Dict[str, Tuple[int, bool]] = {
            n: (c, False) for n, c in st.param_class.items()
        }
        self.ctx: List[int] = []  # enclosing loop bound classes
        self.terms: Set[Tuple[int, ...]] = set()
        self.ret_class: int = CONST
        self.ret_stable: bool = False
        self.sites: Dict[Tuple[int, int], CallSite] = {
            (s.lineno, s.col): s for s in fi.calls
        }

    def run(self) -> None:
        for node in self.fi.node.body:
            self.stmt(node)

    # -- env helpers --

    def _cls(self, name: str) -> int:
        return self.env.get(name, (CONST, False))[0]

    def _stable(self, name: str) -> bool:
        return self.env.get(name, (CONST, False))[1]

    def _assign_name(self, name: str, cls: int, stable: bool) -> None:
        self.env[name] = (cls, stable)

    def _assign_target(self, tgt, cls: int, stable: bool) -> None:
        if isinstance(tgt, ast.Name):
            self._assign_name(tgt.id, cls, stable)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._assign_target(elt, cls, stable)
        elif isinstance(tgt, ast.Starred):
            self._assign_target(tgt.value, cls, stable)
        elif isinstance(tgt, (ast.Subscript, ast.Attribute)):
            if isinstance(tgt, ast.Subscript):
                self.expr(tgt.slice)
            base = tgt.value
            while isinstance(base, (ast.Attribute, ast.Subscript)):
                base = base.value
            if isinstance(base, ast.Name):
                bcls, bstab = self.env.get(base.id, (CONST, False))
                self.env[base.id] = (max(bcls, cls), bstab or stable)

    # -- terms --

    def _add_term(self, factors: List[int], node, via: str = "") -> None:
        term = _mk_term(factors)
        if not term:
            return
        self.terms.add(term)
        # superlinear fires exactly when the new factor/fold completes
        # the second lin-or-worse factor (the enclosing context alone
        # was not yet superlinear — no cascade re-reports)
        if _lin_count(term) >= 2 and _lin_count(tuple(self.ctx)) < 2:
            detail = (
                f"per-request cost term `{render_term(term)}`: nested "
                "non-const bounds — one request buys work proportional "
                "to the product"
            )
            if via:
                detail += f" (via {via})"
            self.eng.report("cost-superlinear", self.key, node, detail)

    # -- statements --

    def stmt(self, node: ast.AST) -> None:
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            return
        if isinstance(node, ast.Assign):
            cls, stable = self.expr2(node.value)
            for tgt in node.targets:
                self._assign_target(tgt, cls, stable)
        elif isinstance(node, ast.AnnAssign):
            cls, stable = (
                self.expr2(node.value) if node.value else (CONST, False)
            )
            self._assign_target(node.target, cls, stable)
        elif isinstance(node, ast.AugAssign):
            cls, stable = self.expr2(node.value)
            if isinstance(node.target, ast.Name):
                cur, curst = self.env.get(node.target.id, (CONST, False))
                self._assign_name(
                    node.target.id, max(cur, cls), curst or stable
                )
            else:
                self._assign_target(node.target, cls, stable)
        elif isinstance(node, ast.Expr):
            self.expr(node.value)
        elif isinstance(node, ast.Return):
            if node.value is not None:
                cls, stable = self.expr2(node.value)
                self.ret_class = max(self.ret_class, cls)
                self.ret_stable = self.ret_stable or stable
        elif isinstance(node, ast.If):
            self._branch(node.test, node.body, node.orelse)
        elif isinstance(node, ast.While):
            self._while(node)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            self._for(node)
        elif isinstance(node, ast.Try):
            for s in node.body:
                self.stmt(s)
            for h in node.handlers:
                for s in h.body:
                    self.stmt(s)
            for s in node.orelse:
                self.stmt(s)
            for s in node.finalbody:
                self.stmt(s)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                self.expr(item.context_expr)
            for s in node.body:
                self.stmt(s)
        elif isinstance(node, ast.Assert):
            self.expr(node.test)
            self._reclass_test(node.test)
            if node.msg is not None:
                self.expr(node.msg)
        elif isinstance(node, ast.Raise):
            if node.exc is not None:
                self.expr(node.exc)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.env.pop(t.id, None)
                else:
                    self.expr(t)
        elif isinstance(
            node,
            (ast.Global, ast.Nonlocal, ast.Pass, ast.Break, ast.Continue,
             ast.Import, ast.ImportFrom),
        ):
            return
        else:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self.expr(child)
                elif isinstance(child, ast.stmt):
                    self.stmt(child)

    def _branch(self, test, body, orelse) -> None:
        self.expr(test)
        self._reclass_test(test)
        snap = dict(self.env)
        for s in body:
            self.stmt(s)
        env_b = self.env
        self.env = dict(snap)
        for s in orelse:
            self.stmt(s)
        # join: worst class / any-stability survives
        for name, (cls, stab) in env_b.items():
            cur, curst = self.env.get(name, (CONST, False))
            self.env[name] = (max(cur, cls), curst or stab)

    def _loop_body(self, body) -> None:
        # two joined passes so a name bound late in the body is seen by
        # earlier uses on the next iteration
        for _ in range(2):
            for s in body:
                self.stmt(s)

    def _while(self, node: ast.While) -> None:
        self.expr(node.test)
        # a while loop is a cost factor only when its test reads an
        # attacker/store-classed counter; event loops (`while True`,
        # `while not closed.is_set()`) are the serving boundary
        bound = CONST
        for cmp_node in ast.walk(node.test):
            if not isinstance(cmp_node, ast.Compare):
                continue
            for side in [cmp_node.left] + list(cmp_node.comparators):
                for n in ast.walk(side):
                    if isinstance(n, ast.Name):
                        c = self._cls(n.id)
                        if c >= STORE:
                            bound = max(bound, c)
        if bound >= STORE:
            self._add_term(self.ctx + [bound], node)
            self.ctx.append(bound)
            self._loop_body(node.body)
            self.ctx.pop()
        else:
            self._loop_body(node.body)
        for s in node.orelse:
            self.stmt(s)

    def _bound_of_iter(self, iter_node: ast.AST) -> int:
        if _iter_clamped(iter_node):
            return CLAMPED
        cls, _ = self.expr2(iter_node)
        return cls

    def _for(self, node) -> None:
        # a p2p root's own `async for envelope in <channel>` loop is
        # the per-request boundary, not a cost factor
        boundary = (
            isinstance(node, ast.AsyncFor)
            and self.is_p2p_root
            and isinstance(node.target, ast.Name)
            and node.target.id == "envelope"
        )
        bound = CONST if boundary else self._bound_of_iter(node.iter)
        _, iter_stable = self.expr2(node.iter)
        if boundary:
            self._assign_target(node.target, ATTACKER, False)
        else:
            # the element of an attacker-sized collection is attacker
            # content; elements of protocol collections are one item
            elem_cls = ATTACKER if bound == ATTACKER else CONST
            self._assign_target(node.target, elem_cls, iter_stable)
        if bound >= CLAMPED:
            self._add_term(self.ctx + [bound], node)
            self.ctx.append(bound)
            self._loop_body(node.body)
            self.ctx.pop()
        else:
            self._loop_body(node.body)
        for s in node.orelse:
            self.stmt(s)

    # -- re-classification (the guard-then-raise idiom) --

    def _reclass_test(self, test: ast.AST) -> None:
        """A comparison between a lin-or-worse name and a lower-class
        expression bounds the name by that expression for the rest of
        the function: `if height > top: raise` pins an attacker height
        into the store range; `if 0 < n < CAP` clamps it. Identity
        tests bound nothing (the tmsafe is-exemption, re-applied)."""
        for node in ast.walk(test):
            if not isinstance(node, ast.Compare):
                continue
            if any(
                isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                for op in node.ops
            ):
                continue
            sides = [node.left] + list(node.comparators)
            side_cls = [self.expr(s) for s in sides]
            floor = min(side_cls)
            for side in sides:
                for n in ast.walk(side):
                    if not isinstance(n, ast.Name):
                        continue
                    cur, stab = self.env.get(n.id, (CONST, False))
                    if cur >= LIN and floor < cur:
                        new = CLAMPED if floor <= CLAMPED else floor
                        self.env[n.id] = (new, stab)

    # -- expressions --

    def expr(self, node: Optional[ast.AST]) -> int:
        return self.expr2(node)[0]

    def expr2(self, node: Optional[ast.AST]) -> Tuple[int, bool]:
        if node is None:
            return CONST, False
        if isinstance(node, ast.Constant):
            return CONST, False
        if isinstance(node, ast.Name):
            if node.id in self.env:
                return self.env[node.id]
            if _is_screaming(node.id):
                return CONST, False
            if node.id in VSET_MARKERS:
                return VSET, False
            if node.id in BLOCK_MARKERS:
                return BLOCK, False
            return LIN, False
        if isinstance(node, ast.Attribute):
            vcls, vstab = self.expr2(node.value)
            if _is_screaming(node.attr):
                return CONST, vstab
            if node.attr in VSET_MARKERS:
                return VSET, vstab
            if node.attr in BLOCK_MARKERS:
                return BLOCK, vstab
            if vcls == ATTACKER:
                # fields of an attacker message are attacker-chosen
                return ATTACKER, vstab
            return LIN, vstab
        if isinstance(node, ast.Await):
            return self.expr2(node.value)
        if isinstance(node, ast.Starred):
            return self.expr2(node.value)
        if isinstance(node, ast.BinOp):
            lc, ls = self.expr2(node.left)
            rc, rs = self.expr2(node.right)
            if isinstance(node.op, ast.Mult):
                self._check_repeat_alloc(node, lc, rc)
            if isinstance(node.op, ast.Mod) and rc <= CLAMPED:
                # v % bound pins v
                return min(lc, CLAMPED), ls or rs
            return max(lc, rc), ls or rs
        if isinstance(node, ast.UnaryOp):
            return self.expr2(node.operand)
        if isinstance(node, ast.BoolOp):
            cls, stab = CONST, False
            for v in node.values:
                c, s = self.expr2(v)
                cls, stab = max(cls, c), stab or s
            return cls, stab
        if isinstance(node, ast.Compare):
            self.expr(node.left)
            for c in node.comparators:
                self.expr(c)
            return CONST, False
        if isinstance(node, ast.IfExp):
            self.expr(node.test)
            self._reclass_test(node.test)
            bc, bs = self.expr2(node.body)
            oc, os_ = self.expr2(node.orelse)
            return max(bc, oc), bs or os_
        if isinstance(node, ast.Subscript):
            vcls, vstab = self.expr2(node.value)
            if isinstance(node.slice, ast.Slice):
                self.expr(node.slice.lower)
                self.expr(node.slice.upper)
                self.expr(node.slice.step)
                up = node.slice.upper
                upname = ""
                if isinstance(up, ast.Name):
                    upname = up.id
                elif isinstance(up, ast.Attribute):
                    upname = up.attr
                if up is not None and (
                    isinstance(up, ast.Constant) or _is_screaming(upname)
                ):
                    return CLAMPED, vstab
                # the pagination idiom `x[start : start + per_page]`:
                # slice LENGTH is bounded by per_page even when start
                # is attacker-chosen
                if (
                    isinstance(up, ast.BinOp)
                    and isinstance(up.op, ast.Add)
                    and node.slice.lower is not None
                ):
                    low_src = ast.dump(node.slice.lower)
                    for base_side, len_side in (
                        (up.left, up.right),
                        (up.right, up.left),
                    ):
                        if (
                            ast.dump(base_side) == low_src
                            and self.expr(len_side) <= CLAMPED
                        ):
                            return CLAMPED, vstab
                return vcls, vstab
            self.expr(node.slice)
            if vcls == ATTACKER:
                return ATTACKER, vstab
            return (LIN if vcls >= LIN else CONST), vstab
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
            cls, stab = CONST, False
            for e in node.elts:
                c, s = self.expr2(e)
                cls, stab = max(cls, c), stab or s
            return cls, stab
        if isinstance(node, ast.Dict):
            cls, stab = CONST, False
            for k in node.keys:
                if k is not None:
                    c, s = self.expr2(k)
                    cls, stab = max(cls, c), stab or s
            for v in node.values:
                c, s = self.expr2(v)
                cls, stab = max(cls, c), stab or s
            return cls, stab
        if isinstance(
            node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
        ):
            return self._comprehension(node)
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                self.expr(v)
            return CONST, False
        if isinstance(node, ast.FormattedValue):
            self.expr(node.value)
            return CONST, False
        if isinstance(node, ast.Lambda):
            return CONST, False
        if isinstance(node, ast.Slice):
            self.expr(node.lower)
            self.expr(node.upper)
            self.expr(node.step)
            return CONST, False
        if isinstance(node, ast.NamedExpr):
            cls, stab = self.expr2(node.value)
            self._assign_target(node.target, cls, stab)
            return cls, stab
        cls, stab = CONST, False
        for c in ast.iter_child_nodes(node):
            if isinstance(c, ast.expr):
                cc, cs = self.expr2(c)
                cls, stab = max(cls, cc), stab or cs
        return cls, stab

    def _comprehension(self, node) -> Tuple[int, bool]:
        pushed = 0
        stab_any = False
        for gen in node.generators:
            bound = self._bound_of_iter(gen.iter)
            _, iter_stable = self.expr2(gen.iter)
            stab_any = stab_any or iter_stable
            elem_cls = ATTACKER if bound == ATTACKER else CONST
            self._assign_target(gen.target, elem_cls, iter_stable)
            if bound >= CLAMPED:
                self._add_term(self.ctx + [bound], gen.iter)
                self.ctx.append(bound)
                pushed += 1
            for cond in gen.ifs:
                self.expr(cond)
                self._reclass_test(cond)
        try:
            if isinstance(node, ast.DictComp):
                kc, ks = self.expr2(node.key)
                vc, vs = self.expr2(node.value)
                cls, stab = max(kc, vc), ks or vs
            else:
                cls, stab = self.expr2(node.elt)
        finally:
            for _ in range(pushed):
                self.ctx.pop()
        # the comprehension RESULT is a collection bounded by its
        # outermost generator; its elements' stability propagates
        bound0 = self._bound_of_iter(node.generators[0].iter)
        return max(bound0, CONST), stab or stab_any

    # -- calls --

    def _call(self, node: ast.Call) -> Tuple[int, bool]:
        func = node.func
        recv_cls, recv_stab = CONST, False
        attr = ""
        if isinstance(func, ast.Attribute):
            recv_cls, recv_stab = self.expr2(func.value)
            attr = func.attr
        arg_pairs = [self.expr2(a) for a in node.args]
        kw_pairs: Dict[str, Tuple[int, bool]] = {}
        spread = (CONST, False)
        for kw in node.keywords:
            p = self.expr2(kw.value)
            if kw.arg is not None:
                kw_pairs[kw.arg] = p
            else:
                spread = (max(spread[0], p[0]), spread[1] or p[1])
        arg_classes = [c for c, _ in arg_pairs]
        all_pairs = arg_pairs + list(kw_pairs.values()) + [spread]
        max_arg = max([CONST] + [c for c, _ in all_pairs])
        any_stable = any(s for _, s in all_pairs)

        name = func.id if isinstance(func, ast.Name) else ""

        # accumulating a stable value into a local container makes the
        # container stable (`blocks.append(lb)` — the page the response
        # constructor will wrap); the two-pass loop body makes earlier
        # uses see it
        if (
            attr in ("append", "extend", "add", "insert", "appendleft",
                     "update", "setdefault")
            and any_stable
            and isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
        ):
            rname = func.value.id
            rcls, _ = self.env.get(rname, (CONST, False))
            self.env[rname] = (rcls, True)

        # builtins with bound semantics
        if name == "len":
            return max_arg, False
        if name in ("int", "abs", "ord", "round"):
            return max_arg, False
        if name == "min" and arg_classes:
            lo = min(arg_classes)
            hi = max(arg_classes)
            if lo <= CLAMPED and hi > lo:
                return CLAMPED, False  # the clamp expression itself
            return lo, False
        if name == "max" and arg_classes:
            return max(arg_classes), False
        if name == "range":
            return max_arg, False
        if name in ("bytes", "bytearray"):
            if (
                arg_classes
                and arg_classes[0] >= STORE
            ):
                self.eng.report(
                    "cost-unclamped-alloc",
                    self.key,
                    node,
                    f"`{name}()` sized by an unclamped "
                    f"`{CLASS_NAMES[arg_classes[0]]}`-class bound — "
                    "allocation proportional to an unbounded input",
                )
            return CONST, any_stable
        if name in ("sorted", "list", "tuple", "set", "frozenset",
                    "reversed", "enumerate", "zip", "sum", "map",
                    "filter", "dict"):
            return max_arg, any_stable
        if name in ("str", "repr", "bool", "float", "hex", "isinstance",
                    "hasattr", "getattr", "print", "type", "format",
                    "id"):
            return CONST, False

        # attribute families
        if attr:
            if attr in ("items", "values", "keys", "copy"):
                return recv_cls, recv_stab
            if attr in ("get", "pop", "setdefault") and recv_cls == ATTACKER:
                # params.get(...) hands back an attacker-chosen value
                return ATTACKER, recv_stab
            if attr in ("height", "base", "size") and _is_store_recv(
                getattr(func, "value", None)
            ):
                return STORE, False
            if attr.startswith(_STORE_LOAD_PREFIXES) and _is_store_recv(
                getattr(func, "value", None)
            ):
                # a store load: per-block-immutable content
                return LIN, True

        site = self.sites.get((node.lineno, node.col_offset))
        target = site.target if site is not None else None

        # -- cost-recompute: expensive pure work on stable inputs --
        # an encoder's own recursion (to_proto calling its children's
        # to_proto) is not a separate recompute: the finding belongs at
        # the serving-side call that re-enters the encoder per request
        in_encoder = self.fi.qualname.split(".")[-1] in EXPENSIVE_ATTRS
        if not self.in_cache_module and not in_encoder:
            expensive = (
                attr in EXPENSIVE_ATTRS and recv_stab
            ) or (
                target in EXPENSIVE_TARGETS
                and (recv_stab or any_stable)
            )
            if expensive:
                what = attr or (target[1] if target else name)
                self.eng.report(
                    "cost-recompute",
                    self.key,
                    node,
                    f"`{what}` on a store-derived (per-block-immutable) "
                    "value inside the serving region — cacheable work "
                    "paid per request (hold it in the per-block serving "
                    "cache instead)",
                )

        if target is not None:
            return self._internal_call(
                node, target, arg_pairs, kw_pairs, (recv_cls, recv_stab),
                max_arg, any_stable,
            )

        # unknown/external: result bounded by the inputs; stability
        # survives pure transformation (`.hex()`, `b"".join(...)`)
        return max(recv_cls if recv_cls == ATTACKER else CONST,
                   CONST), recv_stab or any_stable

    def _internal_call(
        self, node, target: FuncKey, arg_pairs, kw_pairs, recv_pair,
        max_arg: int, any_stable: bool,
    ) -> Tuple[int, bool]:
        callee = self.eng.pkg.functions.get(target)
        if callee is None:
            return CONST, any_stable
        classes: Dict[str, int] = {}
        args = callee.node.args
        positional = [a.arg for a in args.posonlyargs + args.args]
        params = positional + [a.arg for a in args.kwonlyargs]
        pos = list(positional)
        if pos and pos[0] in ("self", "cls"):
            if recv_pair[0] > CONST:
                classes[pos[0]] = recv_pair[0]
            pos = pos[1:]
        for i, (cls, _stab) in enumerate(arg_pairs):
            if i < len(pos):
                if cls > CONST:
                    classes[pos[i]] = max(classes.get(pos[i], CONST), cls)
        for kname, (cls, _stab) in kw_pairs.items():
            if kname in params:
                if cls > CONST:
                    classes[kname] = max(classes.get(kname, CONST), cls)
        if target == self.key:
            # recursion: no self-fold (the tmsafe recursion rule owns
            # attacker-driven depth); return current summary
            st = self.eng._state(target)
            return st.ret_class, st.ret_stable
        st = self.eng._flow_into(
            self.key, target, classes, node.lineno
        )
        # fold the callee's cost terms under the enclosing loop context
        if st.terms:
            via = self.eng.pkg.functions[target].render()
            for t in st.terms:
                self._add_term(self.ctx + list(t), node, via=via)
        if target[1].endswith(".__init__"):
            # constructor: the instance wraps its (possibly stable) args
            return CONST, recv_pair[1] or any_stable
        return st.ret_class, st.ret_stable

    def _check_repeat_alloc(self, node, lc: int, rc: int) -> None:
        for seq_side, n_cls in (
            (node.left, rc),
            (node.right, lc),
        ):
            if n_cls < STORE:
                continue
            if (
                isinstance(seq_side, ast.Constant)
                and isinstance(seq_side.value, (str, bytes))
            ) or isinstance(seq_side, (ast.List, ast.Tuple)):
                self.eng.report(
                    "cost-unclamped-alloc",
                    self.key,
                    node,
                    "sequence repetition sized by an unclamped "
                    f"`{CLASS_NAMES[n_cls]}`-class bound — allocation "
                    "proportional to an unbounded input",
                )
                return
