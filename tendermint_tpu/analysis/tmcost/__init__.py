"""tmcost — whole-program per-request cost-bound proof.

The six analyzers before this one (PRs 4–10) prove what serving code
*does*; tmcost proves how much a single request is ALLOWED TO COST.
Stateless-client workloads hammer a node with repeated proof/header
requests (arxiv 2504.14069), and commit-verification cost as a
function of committee size is the paper's central trade (arxiv
2302.00418) — so every serving root (RPC route handler, p2p recv
handler, per-block consensus entry point) gets a symbolic per-request
cost class derived by an interprocedural loop-bound **provenance**
dataflow (boundflow.py) and checked against the reviewed golden budget
table `cost_budgets.json`.

Rules:

- ``cost-superlinear`` — a request's cost term acquires two
  lin-or-worse factors (nested unbounded bounds); the static twin of
  tmsafe's quadratic-decode, over OUR loops, not just attacker taint.
- ``cost-recompute`` — known-expensive pure work (to_proto / hash /
  merkle-tree / page assembly) on a store-derived per-block-immutable
  value inside the serving region: cacheable work paid per request.
  The serving cache (rpc/servingcache.py) is the sanctioned memo
  layer and is exempt (its miss path is where that work belongs).
- ``cost-unclamped-alloc`` — allocation proportional to a
  store-or-worse bound with no clamp.
- ``cost-budget`` — GOLDEN-GATED (never baselineable, the tmtrace
  drift-rule class): a serving root missing from `cost_budgets.json`,
  a computed cost differing from the reviewed budget (either
  direction — a cheaper route is also a reviewed change), or a stale
  table entry. Reviewed update via `scripts/lint.py --cost-update`
  (refused on filtered/combined runs, the established matrix).

Suppressions: ``# tmcost: <rule>-ok — why`` on the offending line or
in the comment block above (comment_cover_lines, shared family-wide).
Counted fingerprint baseline `cost_baseline.json` ships — and is
pinned by test — EMPTY.

Run via `scripts/lint.py --cost` (in the default full gate). The
dynamic twin is the tmload harness (docs/load.md): tmcost bounds what
a request MAY cost by construction; tmload measures what it DOES cost
under production traffic. The division of labor is documented in
docs/static_analysis.md.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from ..tmlint import (
    Violation,
    comment_cover_lines,
    load_baseline,
    new_violations,
    save_baseline,
)
from ..tmcheck.callgraph import Package, build_package
from . import boundflow, roots as roots_mod  # noqa: F401
from .boundflow import CostEngine
from .roots import CONSENSUS_ROOTS, Root, discover_roots, root_id

__all__ = [
    "RULES",
    "NON_BASELINE_RULES",
    "BUDGETS_PATH",
    "COST_BASELINE_PATH",
    "COST_BASELINE_NOTE",
    "CostReport",
    "analyze",
    "cost_violations",
    "new_cost_violations",
    "update_cost_baseline",
    "load_budgets",
    "update_budgets",
    "split_baselineable",
    "suppressed_lines",
]

BUDGETS_PATH = os.path.join(os.path.dirname(__file__), "cost_budgets.json")
COST_BASELINE_PATH = os.path.join(
    os.path.dirname(__file__), "cost_baseline.json"
)

COST_BASELINE_NOTE = (
    "Accepted pre-existing per-request cost findings, fingerprinted by "
    "rule:path:sha1(source_line)[:12]. New findings are anything over "
    "these counts. Do not hand-edit counts to sneak a finding in — fix "
    "it, or suppress it in-file with a justified "
    "'# tmcost: <rule>-ok — why'. cost-budget findings can NEVER land "
    "here: their accepted state is cost_budgets.json "
    "(scripts/lint.py --cost-update)."
)

BUDGETS_NOTE = (
    "Reviewed per-request cost budgets for every serving root. The "
    "cost strings are boundflow terms (provenance classes joined by "
    "'*'); the gate fails on ANY drift — a new root, a removed root, "
    "or a changed cost in either direction. Update via scripts/lint.py "
    "--cost-update and REVIEW the diff: a budget raise is a product "
    "decision, not a formality."
)

RULES = [
    (
        "cost-superlinear",
        "a per-request cost term with two known-unbounded "
        "(vset-or-worse) factors: nested unbounded iteration paid per "
        "request",
    ),
    (
        "cost-recompute",
        "known-expensive pure work (to_proto/hash/merkle/page assembly) "
        "on per-block-immutable store content, recomputed per request "
        "instead of held in the serving cache",
    ),
    (
        "cost-unclamped-alloc",
        "allocation proportional to a store-or-worse bound with no "
        "clamp between derivation and use",
    ),
    (
        "cost-budget",
        "serving root missing from cost_budgets.json, computed cost "
        "drifting from the reviewed budget, or a stale budget entry "
        "(golden-gated: fix or --cost-update, never baselineable)",
    ),
]

NON_BASELINE_RULES = frozenset({"cost-budget"})

_SUPPRESS_RE = re.compile(r"#\s*tmcost:\s*(cost-[a-z\-]+)-ok\b")


def suppressed_lines(lines: List[str]) -> Dict[str, Set[int]]:
    """rule -> covered line numbers for `# tmcost: <rule>-ok — why`
    annotations (same comment-block-above convention as the family)."""
    out: Dict[str, Set[int]] = {}
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        out.setdefault(m.group(1), set()).update(
            comment_cover_lines(lines, i, text)
        )
    return out


def split_baselineable(violations: List[Violation]):
    """(baselineable, golden_gated): cost-budget findings can never be
    absorbed by the counted baseline — their accepted state is the
    budget table itself (same class as tmtrace's drift rules)."""
    base = [v for v in violations if v.rule not in NON_BASELINE_RULES]
    gated = [v for v in violations if v.rule in NON_BASELINE_RULES]
    return base, gated


def load_budgets(path: Optional[str] = None) -> Dict[str, dict]:
    path = path or BUDGETS_PATH
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    return data.get("roots", {})


class CostReport:
    def __init__(self) -> None:
        self.roots: List[Root] = []
        self.engine: Optional[CostEngine] = None
        self.findings: List[boundflow.Finding] = []
        self.costs: Dict[str, dict] = {}  # root_id -> {family, cost}
        self.violations: List[Violation] = []
        self.stats: Dict[str, int] = {}
        # (rule, path, line) of findings dropped by an in-file
        # suppression — the head-catalog test pins this set
        self.suppressed: List[tuple] = []


def _computed_costs(
    engine: CostEngine, roots: List[Root]
) -> Dict[str, dict]:
    out: Dict[str, dict] = {}
    for r in roots:
        out[root_id(r.key)] = {
            "family": r.family,
            "cost": engine.cost_of(r.key),
        }
    return out


def analyze(
    pkg: Optional[Package] = None,
    budgets_path: Optional[str] = None,
) -> CostReport:
    pkg = pkg or build_package()
    report = CostReport()
    report.roots = discover_roots(pkg)
    engine = CostEngine(pkg, report.roots)
    report.engine = engine
    findings = engine.run()
    report.findings = findings
    report.costs = _computed_costs(engine, report.roots)

    supp: Dict[str, Dict[str, Set[int]]] = {}
    for path, mod in pkg.modules.items():
        m = suppressed_lines(mod.lines)
        if m:
            supp[path] = m

    def line_text(path: str, lineno: int) -> str:
        lines = pkg.modules[path].lines
        if 1 <= lineno <= len(lines):
            return lines[lineno - 1].strip()
        return ""

    violations: List[Violation] = []
    n_supp = 0
    for f in findings:
        if f.lineno in supp.get(f.path, {}).get(f.rule, ()):
            n_supp += 1
            report.suppressed.append((f.rule, f.path, f.lineno))
            continue
        chain = engine.chain(f.key)
        violations.append(
            Violation(
                rule=f.rule,
                path=f.path,
                line=f.lineno,
                col=f.col,
                message=f"{f.detail}; witness: {' -> '.join(chain)}",
                source=line_text(f.path, f.lineno),
            )
        )

    # -- the budget gate (golden; drift in either direction is red) --
    budgets = load_budgets(budgets_path)
    for rid, rec in sorted(report.costs.items()):
        key = tuple(rid.split(":", 1))
        fi = pkg.functions.get(key)  # roots always resolve
        lineno = fi.lineno if fi is not None else 1
        src = line_text(key[0], lineno) if fi is not None else ""
        golden = budgets.get(rid)
        if golden is None:
            violations.append(
                Violation(
                    rule="cost-budget",
                    path=key[0],
                    line=lineno,
                    col=0,
                    message=(
                        f"serving root {rid} [{rec['family']}] has no "
                        "reviewed cost budget (computed: "
                        f"{rec['cost']}); a new route cannot ship "
                        "unbudgeted — review and run scripts/lint.py "
                        "--cost-update"
                    ),
                    source=src,
                )
            )
        elif golden.get("cost") != rec["cost"] or golden.get(
            "family"
        ) != rec["family"]:
            violations.append(
                Violation(
                    rule="cost-budget",
                    path=key[0],
                    line=lineno,
                    col=0,
                    message=(
                        f"cost drift at {rid}: computed {rec['cost']} "
                        f"[{rec['family']}] vs budgeted "
                        f"{golden.get('cost')} [{golden.get('family')}]"
                        " — fix the regression or review with "
                        "--cost-update"
                    ),
                    source=src,
                )
            )
    for rid in sorted(set(budgets) - set(report.costs)):
        violations.append(
            Violation(
                rule="cost-budget",
                path=rid.split(":", 1)[0],
                line=1,
                col=0,
                message=(
                    f"stale budget entry {rid}: no such serving root "
                    "in the package — remove it via --cost-update"
                ),
                source=rid,
            )
        )

    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    report.violations = violations
    per_rule: Dict[str, int] = {rid: 0 for rid, _ in RULES}
    for v in violations:
        per_rule[v.rule] = per_rule.get(v.rule, 0) + 1
    report.stats = {
        "roots": len(report.roots),
        "region": sum(
            1 for st in engine.states.values() if st.analyzed
        ),
        "suppressed": n_supp,
        "budgeted": len(budgets),
        **{f"findings[{rid}]": n for rid, n in per_rule.items()},
    }
    return report


def cost_violations(pkg: Optional[Package] = None) -> List[Violation]:
    return analyze(pkg).violations


def new_cost_violations(
    pkg: Optional[Package] = None,
    baseline_path: Optional[str] = None,
) -> List[Violation]:
    """Counted-baseline diff for the dataflow rules, PLUS every
    golden-gated cost-budget finding (those are always new)."""
    violations = cost_violations(pkg)
    base, gated = split_baselineable(violations)
    baseline = load_baseline(baseline_path or COST_BASELINE_PATH)
    return new_violations(base, baseline) + gated


def update_cost_baseline(
    pkg: Optional[Package] = None,
    baseline_path: Optional[str] = None,
) -> Dict[str, int]:
    base, _gated = split_baselineable(cost_violations(pkg))
    return save_baseline(
        base,
        baseline_path or COST_BASELINE_PATH,
        note=COST_BASELINE_NOTE,
    )


def update_budgets(
    pkg: Optional[Package] = None,
    path: Optional[str] = None,
) -> Dict[str, dict]:
    """Regenerate the golden budget table from the live analysis —
    the reviewed-update half of the cost-budget gate."""
    report = analyze(pkg, budgets_path=path)
    data = {"note": BUDGETS_NOTE, "roots": report.costs}
    out = path or BUDGETS_PATH
    with open(out, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return data
