"""Correctness tooling: tmlint (AST static analyzer) + lockwatch
(runtime lock-order observer).

The reference enforces its concurrency and determinism invariants
mechanically — `go test -race` in CI plus `go vet` on every target.
This package is the reproduction's equivalent, built for THIS
codebase's hazard surface:

- `tmlint` — stdlib-`ast` static rules over three invariant classes:
  determinism of consensus-critical byte streams (sign-bytes, hashes,
  proto encodings must be replica-identical), lock discipline in the
  threaded device path, and device hygiene on the JAX hot path
  (implicit host syncs, recompile-forcing shape leaks). Run via
  `python scripts/lint.py`; gated in tier-1 by tests/test_lint.py.

- `lockwatch` — wraps `threading.Lock`/`RLock` during tests, records
  the per-thread lock-acquisition graph, and reports ordering cycles
  (Go-lockrank style), rank-table violations, and holds that exceed
  the fast-path budget. Enabled for the chaos/fault/fuzz suites by an
  autouse conftest fixture.

docs/static_analysis.md has the rule catalog, baseline workflow, and
suppression policy.
"""

from . import lockwatch, tmlint  # noqa: F401

__all__ = ["tmlint", "lockwatch"]
