"""tmtrace — whole-program device-dispatch proof.

The TPU claim has been wedged for rounds; the dispatch layer is the
code that executes *least* yet carries the north-star number, so a
trace error or recompilation storm discovered mid-claim burns the one
granted hour. PRs 4-6 machine-proved the consensus side (sign-bytes
taint, wire schemas, races); tmtrace is the same move applied to the
JAX side, on the same substrate (the PR-5 call graph):

1. **Jit-root discovery** (`jitroots.py`): every `jax.jit` site in
   the package, with resolved targets, static args, donations, and
   the *traced region* (functions reachable from jit targets).
2. **Trace-stability dataflow** (`shapeflow.py`): interprocedural
   ARRAY taint flags Python control flow / host conversions on
   abstract values anywhere in the traced region
   (`trace-tracer-leak`, the widening of tmlint's local
   dev-host-sync); the migrated `dev-host-sync` keeps its dispatch
   scope; `dev-shape-leak` is widened to ops/ with a three-valued
   bucket-provenance dataflow so only shapes PROVABLY drawn from the
   pad-bucket table pass.
3. **Recompile-budget gate** (`shapemodel.py`): every root's
   (bucket shape, dtype, static-arg) signature set is enumerated
   from the live config into the golden `jit_signatures.json`;
   drift — a new root, a new bucket, a changed static arg — fails
   tier-1 (`trace-signature-drift` / `trace-unknown-root`).
4. **Sharding consistency** (`shardcheck.py`): PartitionSpec axes
   must exist in a declared Mesh (`trace-mesh-axis`), every bucket
   must divide by every virtual mesh width through the REAL rounding
   code (`trace-bucket-indivisible`), donated buffers must not be
   read after dispatch (`trace-donated-reuse`).
5. **No-TPU compile gate** (`tracegate.py`): `jax.eval_shape` over
   declared root × bucket cases on CPU (`trace-compile-fail`) — the
   fast family in tier-1, the full sweep as the device-campaign
   pre-flight (`scripts/lint.py --trace-full`; its cost is bench.py's
   `trace_all_buckets` row).

Run via `scripts/lint.py --trace` (or the default full gate);
`--signatures-update` regenerates the golden table; suppressions are
`# tmtrace: trace-ok[=rule,...] — why` plus the legacy
`# tmlint: disable=dev-host-sync/dev-shape-leak` forms for the two
migrated rules. tests/test_tmtrace.py holds the tier-1 gates and
seeded-violation fixtures (tests/data/trace/);
docs/static_analysis.md has the catalog and workflow.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Set

from ..tmlint import (
    Violation,
    load_baseline,
    new_violations,
    save_baseline,
)
from ..tmcheck.callgraph import Package, build_package
from . import jitroots, shapeflow, shapemodel, shardcheck, tracegate
from .jitroots import JitRoot, discover
from .shapemodel import GOLDEN_PATH, load_golden, save_golden

__all__ = [
    "RULES",
    "NON_BASELINE_RULES",
    "TRACE_BASELINE_PATH",
    "TRACE_BASELINE_NOTE",
    "GOLDEN_PATH",
    "TraceReport",
    "analyze",
    "trace_violations",
    "new_trace_violations",
    "update_trace_baseline",
    "update_signatures_golden",
]

TRACE_BASELINE_PATH = os.path.join(
    os.path.dirname(__file__), "trace_baseline.json"
)

TRACE_BASELINE_NOTE = (
    "Accepted pre-existing tmtrace findings, fingerprinted by "
    "rule:path:sha1(source_line)[:12]. New findings are anything over "
    "these counts. Do not hand-edit counts to sneak a new finding in "
    "— fix it, or suppress it with a justified '# tmtrace: "
    "trace-ok[=rule] — why' (the migrated dev-host-sync/dev-shape-leak "
    "rules also honor their legacy '# tmlint: disable=<rule>' form). "
    "Signature drift has no baseline: the golden jit_signatures.json "
    "IS the accepted state (scripts/lint.py --signatures-update)."
)

# the tmtrace rule catalog (mirrored by --list-rules and the docs)
RULES = [
    (
        "trace-tracer-leak",
        "Python control flow or host conversion on a traced value "
        "inside the jit-reachable region (interprocedural)",
    ),
    (
        "dev-host-sync",
        "implicit device→host sync in the dispatch layer (migrated "
        "from tmlint, scope unchanged)",
    ),
    (
        "dev-shape-leak",
        "jnp shaped constructor whose shape is not provably drawn "
        "from the pad-bucket table (migrated from tmlint, widened to "
        "ops/ with bucket-provenance dataflow)",
    ),
    (
        "trace-unknown-root",
        "jax.jit root with no declared shape family in the shapemodel",
    ),
    (
        "trace-signature-drift",
        "enumerated (root, bucket shape, dtype, static-arg) signature "
        "set differs from the golden jit_signatures.json",
    ),
    (
        "trace-mesh-axis",
        "PartitionSpec axis name not declared by any Mesh",
    ),
    (
        "trace-bucket-indivisible",
        "a sharded verifier bucket does not divide by a virtual mesh "
        "width (proven against the real rounding code)",
    ),
    (
        "trace-donated-reuse",
        "buffer read after being donated to a jit program",
    ),
    (
        "trace-compile-fail",
        "a declared jit root × bucket fails jax.eval_shape on CPU",
    ),
]

# Rules whose accepted state is the golden jit_signatures.json (or a
# fixed trace), NOT the counted baseline: letting a routine
# --baseline-update fingerprint these would silently accept a
# recompile-budget change or an untraceable root without the reviewed
# --signatures-update path ever running — the same laundering class
# the PR-5 "--schema --baseline-update refused" fix closed.
NON_BASELINE_RULES = frozenset(
    {"trace-signature-drift", "trace-unknown-root", "trace-compile-fail"}
)


def split_baselineable(violations):
    """(baselineable, golden_gated): the second list can never be
    absorbed by a counted baseline."""
    base = [v for v in violations if v.rule not in NON_BASELINE_RULES]
    gated = [v for v in violations if v.rule in NON_BASELINE_RULES]
    return base, gated


_TRACE_OK_RE = re.compile(
    r"#\s*tmtrace:\s*trace-ok(?:=([A-Za-z0-9_\-, ]+))?"
)


def suppression_map(lines: List[str]) -> Dict[int, Set[str]]:
    """lineno -> suppressed rule ids ({'all'} for a bare trace-ok).
    Same two forms as tmlint: on the offending line, or in a comment
    block directly above it."""
    out: Dict[int, Set[str]] = {}
    for i, text in enumerate(lines, start=1):
        m = _TRACE_OK_RE.search(text)
        if not m:
            continue
        rules = (
            {r.strip() for r in m.group(1).split(",") if r.strip()}
            if m.group(1)
            else {"all"}
        )
        out.setdefault(i, set()).update(rules)
        if text.lstrip().startswith("#"):
            j = i + 1
            while j <= len(lines) and (
                not lines[j - 1].strip()
                or lines[j - 1].lstrip().startswith("#")
            ):
                j += 1
            if j <= len(lines):
                out.setdefault(j, set()).update(rules)
    return out


class TraceReport:
    def __init__(self) -> None:
        self.roots: List[JitRoot] = []
        self.traced_region: Set = set()
        self.stats: dict = {}
        self.violations: List[Violation] = []


def analyze(
    pkg: Optional[Package] = None,
    golden_path: Optional[str] = None,
    signatures: bool = True,
    live: bool = True,
    full: bool = False,
    live_budget_s: Optional[float] = None,
) -> TraceReport:
    pkg = pkg or build_package()
    report = TraceReport()
    roots = discover(pkg)
    report.roots = roots
    report.traced_region = jitroots.traced_region(pkg, roots)

    violations: List[Violation] = []
    violations.extend(shapeflow.tracer_leak_violations(pkg, roots))
    violations.extend(shapeflow.host_sync_violations(pkg))
    violations.extend(shapeflow.shape_leak_violations(pkg))
    violations.extend(shardcheck.mesh_axis_violations(pkg))
    violations.extend(shardcheck.donated_reuse_violations(pkg, roots))
    # the signature enumeration and the live tier need jax importable
    # (bucket tables come from the live config through pallas_bucket);
    # on a jax-less box the nine static passes above still gate —
    # degrade these two to a RECORDED skip, never an exit-2 crash
    if signatures:
        try:
            violations.extend(
                shapemodel.drift_violations(
                    roots, load_golden(golden_path), pkg
                )
            )
        except ImportError as e:
            report.stats["signatures"] = f"skipped: {e}"
    if live:
        try:
            live_v, stats = tracegate.run(
                roots, full=full, budget_s=live_budget_s
            )
        except ImportError as e:
            report.stats["live_tier"] = f"skipped: {e}"
        else:
            violations.extend(live_v)
            report.stats.update(stats)

    # -- suppressions: # tmtrace: trace-ok[=rule] (any rule) --
    maps: Dict[str, Dict[int, Set[str]]] = {}
    kept: List[Violation] = []
    for v in violations:
        mod = pkg.modules.get(v.path)
        if mod is not None:
            if v.path not in maps:
                maps[v.path] = suppression_map(mod.lines)
            rules = maps[v.path].get(v.line)
            if rules and ("all" in rules or v.rule in rules):
                continue
        kept.append(v)
    kept.sort(key=lambda v: (v.path, v.line, v.rule))
    report.violations = kept
    return report


def trace_violations(
    pkg: Optional[Package] = None, **kwargs
) -> List[Violation]:
    return analyze(pkg, **kwargs).violations


def new_trace_violations(
    pkg: Optional[Package] = None,
    baseline_path: Optional[str] = None,
    **kwargs,
) -> List[Violation]:
    """tmtrace findings beyond the checked-in baseline (same counted
    fingerprint semantics as tmlint/tmcheck/tmrace). Golden-gated
    rules (NON_BASELINE_RULES) are ALWAYS new — their accepted state
    lives in jit_signatures.json, not the baseline."""
    violations = trace_violations(pkg, **kwargs)
    base, gated = split_baselineable(violations)
    baseline = load_baseline(baseline_path or TRACE_BASELINE_PATH)
    out = new_violations(base, baseline) + gated
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out


def update_trace_baseline(
    pkg: Optional[Package] = None,
    baseline_path: Optional[str] = None,
    **kwargs,
) -> Dict[str, int]:
    """Accept the current DATAFLOW findings; golden-gated rules are
    never written (use --signatures-update for those)."""
    base, _gated = split_baselineable(trace_violations(pkg, **kwargs))
    return save_baseline(
        base,
        baseline_path or TRACE_BASELINE_PATH,
        note=TRACE_BASELINE_NOTE,
    )


def update_signatures_golden(
    pkg: Optional[Package] = None, path: Optional[str] = None
) -> dict:
    pkg = pkg or build_package()
    return save_golden(discover(pkg), path)
