"""Trace-stability dataflow: tracer leaks and dynamic shapes.

Three passes over the device scope, each a machine-checked version of
a rule that used to live in review comments (or, for the first two,
in tmlint's per-module scan — folded here so one site is never
reported twice):

1. **trace-tracer-leak** (interprocedural, the widening of tmlint's
   local dev-host-sync): starting from every jit target's array
   parameters, an ARRAY taint is propagated through local dataflow
   and resolved calls across the traced region. Python control flow
   (`if`/`while`/ternary/`assert`) on an ARRAY value, `bool()/int()/
   float()` conversions, `.item()`/`.tolist()`, and `np.asarray`/
   `np.array` on ARRAY values are trace-time errors (ConcretizationError
   or a silent constant-fold) that only detonate when the root is
   finally jitted on a device claim — exactly what the no-TPU gate
   exists to catch *before* the claim. Shape reads (`.shape`, `.ndim`,
   `len()` of a traced array) are static during tracing and do not
   taint.

2. **dev-host-sync** (migrated from tmlint, scope unchanged:
   crypto/batch.py, crypto/tpu_verifier.py, parallel/): implicit
   device→host syncs in the *dispatch* layer — `.item()`, `float(x)`,
   np.asarray/np.array — where they serialize the async pipeline.
   The node engine is rules_device.DevHostSync, evaluated here so
   tmlint no longer registers (= never double-reports) it.

3. **dev-shape-leak** (migrated and widened: dispatch modules + ops/):
   jnp shaped constructors whose shape argument is not provably
   drawn from the pad-bucket configuration. The widening is a
   three-valued provenance dataflow (static / unknown / dynamic):
   constants, SCREAMING names, attributes, `.shape` reads and
   arithmetic over them are static; results of the bucketizer family
   (`bucket_for`, `pallas_bucket`, `*._bucket`) are static — that is
   the pad-bucket table laundering a dynamic `len(batch)` into a
   compiled shape; `len(...)` is dynamic; function parameters take
   the meet of every resolved call site's argument provenance
   (no resolved callers ⇒ static, under-approximate like the rest of
   the call graph — documented). Anything not provably static is
   flagged, preserving tmlint's strictness while the dataflow keeps
   the legitimate `zeros = padded_len - length - 1 - 8` sites green.

Suppressions: `# tmtrace: trace-ok — why` (same line or comment block
above), plus the legacy `# tmlint: disable=dev-host-sync/dev-shape-leak`
forms for the two migrated rules (existing justified sites keep
working).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..tmlint import Module as LintModule
from ..tmlint import Violation, dotted_name
from ..rules_device import _JNP_SHAPED_CTORS, _NP_TRANSFER, DevHostSync
from ..tmcheck.callgraph import FuncInfo, Package, _body_walk
from .jitroots import JitRoot, is_dispatch_scope

__all__ = [
    "tracer_leak_violations",
    "host_sync_violations",
    "shape_leak_violations",
    "LEGACY_DEVICE_FILES",
    "LEGACY_DEVICE_PREFIXES",
]

FuncKey = Tuple[str, str]

# dev-host-sync keeps tmlint's historical scope: the dispatch layer,
# where a sync is a throughput bug. Inside the traced region the same
# constructs are trace errors and trace-tracer-leak owns them.
LEGACY_DEVICE_FILES = {"crypto/batch.py", "crypto/tpu_verifier.py"}
LEGACY_DEVICE_PREFIXES = ("parallel/",)

_BUCKETIZERS = ("bucket_for", "pallas_bucket")

# attribute reads on an array that yield trace-static Python values
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "weak_type"}

_CONVERTERS = {"bool", "int", "float"}


def _line(pkg: Package, path: str, lineno: int) -> str:
    lines = pkg.modules[path].lines
    if 1 <= lineno <= len(lines):
        return lines[lineno - 1].strip()
    return ""


# ---------------------------------------------------------------------------
# pass 1: interprocedural ARRAY taint (trace-tracer-leak)


def _array_params(fi: FuncInfo, root: Optional[JitRoot]) -> Set[str]:
    """The parameters of a jit target that carry traced arrays: the
    ones without defaults, minus declared static args. Config flags
    (`mosaic=False`, `dual_fn=None`) all carry defaults in this
    codebase — a default marks a trace-time constant."""
    args = fi.node.args
    names = [a.arg for a in args.args]
    n_defaults = len(args.defaults)
    positional = names[: len(names) - n_defaults] if n_defaults else names
    out = {n for n in positional if n not in ("self", "cls")}
    if root is not None:
        out -= set(root.static_argnames)
        for i in root.static_argnums:
            if 0 <= i < len(names):
                out.discard(names[i])
    return out


class _TaintPass:
    """One (function, tainted-param-mask) analysis context."""

    def __init__(self, pkg: Package, report: "_Findings") -> None:
        self.pkg = pkg
        self.report = report
        self.done: Set[Tuple[FuncKey, frozenset]] = set()
        self.queue: List[Tuple[FuncKey, frozenset]] = []
        self.parents: Dict[Tuple[FuncKey, frozenset], FuncKey] = {}

    def seed(self, key: FuncKey, params: Iterable[str]) -> None:
        item = (key, frozenset(params))
        if item not in self.done:
            self.done.add(item)
            self.queue.append(item)

    def run(self) -> None:
        while self.queue:
            key, mask = self.queue.pop()
            self._analyze(key, mask)

    # -- per-function analysis --

    def _analyze(self, key: FuncKey, mask: frozenset) -> None:
        fi = self.pkg.functions.get(key)
        if fi is None:
            return
        resolved = {
            (s.lineno, s.col): s.target
            for s in fi.calls
            if s.target is not None
        }
        env: Dict[str, bool] = {n: True for n in mask}

        def flag(node: ast.AST, what: str) -> None:
            self.report.add(
                "trace-tracer-leak",
                fi.path,
                node.lineno,
                f"{what} inside the traced region "
                f"({fi.qualname}, reached from a jax.jit root"
                f"{self._chain_note(key, mask)}) — a trace-time error "
                "on the device path; keep control flow and host "
                "conversions outside jitted bodies "
                "(jnp.where / lax.cond / shape reads are fine)",
                _line(self.pkg, fi.path, node.lineno),
            )

        def tainted(node: ast.AST) -> bool:
            # NO short-circuiting anywhere in here: evaluating a
            # sub-expression is what flags leaks and enqueues
            # interprocedural edges, so every operand must be visited
            # even once the result is known (`x + helper(y)` must
            # still analyze helper when x is already tainted)
            if isinstance(node, ast.Name):
                return env.get(node.id, False)
            if isinstance(node, ast.Constant):
                return False
            if isinstance(node, ast.Attribute):
                # evaluate the receiver FIRST even when the attribute
                # itself is static: `helper(x).shape[0]` must still
                # analyze helper (same no-short-circuit invariant as
                # the operand rules above)
                t = tainted(node.value)
                if node.attr in _STATIC_ATTRS:
                    return False
                return t
            if isinstance(node, ast.Subscript):
                # indexing BY a traced value yields a traced value too
                ts = [tainted(node.value), tainted(node.slice)]
                return any(ts)
            if isinstance(node, ast.BinOp):
                ts = [tainted(node.left), tainted(node.right)]
                return any(ts)
            if isinstance(node, ast.UnaryOp):
                return tainted(node.operand)
            if isinstance(node, ast.Compare):
                ts = [tainted(node.left)] + [
                    tainted(c) for c in node.comparators
                ]
                # identity checks (`x is None`, `prog is _JIT`) test
                # the Python binding, never the abstract value — the
                # `acc = s if acc is None else acc + s` accumulator
                # idiom is trace-safe
                if all(
                    isinstance(op, (ast.Is, ast.IsNot))
                    for op in node.ops
                ):
                    return False
                return any(ts)
            if isinstance(node, ast.BoolOp):
                return any([tainted(v) for v in node.values])
            if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
                return any([tainted(e) for e in node.elts])
            if isinstance(node, ast.Starred):
                return tainted(node.value)
            if isinstance(node, ast.Slice):
                for part in (node.lower, node.upper, node.step):
                    if part is not None:
                        tainted(part)
                return False
            if isinstance(node, ast.IfExp):
                # ternary on a traced value is itself a leak
                if tainted(node.test):
                    flag(node.test, "ternary on a traced value")
                ts = [tainted(node.body), tainted(node.orelse)]
                return any(ts)
            if isinstance(node, ast.Call):
                return self._call(node, tainted, key, mask, resolved)
            return False

        # program-order statement walk: the taint env is built as
        # control flow would (a stack-order ast.walk reads uses before
        # their defs and silently drops every interprocedural edge —
        # found by the propagation-depth test). Loop bodies get TWO
        # passes so loop-carried taint (`state = _compress(state, w)`)
        # converges; findings dedupe by (rule, path, line).
        def do_stmt(st: ast.stmt) -> None:
            if isinstance(
                st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                return  # nested defs are their own (unreached) nodes
            if isinstance(st, ast.Assign):
                t = tainted(st.value)
                for tgt in st.targets:
                    self._bind(tgt, t, env)
            elif isinstance(st, ast.AugAssign):
                if isinstance(st.target, ast.Name):
                    env[st.target.id] = env.get(
                        st.target.id, False
                    ) or tainted(st.value)
                else:
                    tainted(st.value)
            elif isinstance(st, ast.AnnAssign):
                if st.value is not None:
                    self._bind(st.target, tainted(st.value), env)
            elif isinstance(st, ast.If):
                if tainted(st.test):
                    flag(st.test, "Python branch on a traced value")
                walk(st.body)
                walk(st.orelse)
            elif isinstance(st, ast.While):
                if tainted(st.test):
                    flag(st.test, "Python loop on a traced value")
                walk(st.body)
                walk(st.body)
                walk(st.orelse)
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                self._bind(st.target, tainted(st.iter), env)
                walk(st.body)
                walk(st.body)
                walk(st.orelse)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    tainted(item.context_expr)
                walk(st.body)
            elif isinstance(st, ast.Try):
                walk(st.body)
                for h in st.handlers:
                    walk(h.body)
                walk(st.orelse)
                walk(st.finalbody)
            elif isinstance(st, ast.Assert):
                if tainted(st.test):
                    flag(st.test, "assert on a traced value")
            elif isinstance(st, ast.Return):
                if st.value is not None:
                    tainted(st.value)
            elif isinstance(st, ast.Expr):
                tainted(st.value)
            elif isinstance(st, ast.Raise):
                if st.exc is not None:
                    tainted(st.exc)

        def walk(stmts) -> None:
            for st in stmts:
                do_stmt(st)

        walk(fi.node.body)

    def _bind(self, tgt: ast.AST, t: bool, env: Dict[str, bool]) -> None:
        if isinstance(tgt, ast.Name):
            env[tgt.id] = t
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._bind(e, t, env)

    def _chain_note(self, key: FuncKey, mask: frozenset) -> str:
        chain = []
        cur = (key, mask)
        seen = set()
        while cur in self.parents and cur not in seen:
            seen.add(cur)
            parent = self.parents[cur]
            chain.append(parent[1])
            cur = None
            for item in self.done:
                if item[0] == parent:
                    cur = item
                    break
            if cur is None:
                break
        if not chain:
            return ""
        return " via " + " -> ".join(reversed(chain[:4]))

    def _call(
        self,
        node: ast.Call,
        tainted,
        key: FuncKey,
        mask: frozenset,
        resolved: Dict[Tuple[int, int], FuncKey],
    ) -> bool:
        name = dotted_name(node.func)
        arg_taints = [tainted(a) for a in node.args]
        kw_taints = {
            k.arg: tainted(k.value) for k in node.keywords if k.arg
        }
        any_tainted = any(arg_taints) or any(kw_taints.values())
        fi = self.pkg.functions[key]

        def leak(what: str) -> None:
            self.report.add(
                "trace-tracer-leak",
                fi.path,
                node.lineno,
                f"{what} on a traced value inside the traced region "
                f"({fi.qualname}) — concretizes an abstract value at "
                "trace time; gather results on the host side of the "
                "jit boundary instead",
                _line(self.pkg, fi.path, node.lineno),
            )

        if name in _CONVERTERS and any_tainted:
            leak(f"`{name}()`")
            return False
        if name == "len" and any_tainted:
            return False  # len of a traced array is its static dim
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in ("item", "tolist")
            and tainted(node.func.value)
        ):
            leak(f"`.{node.func.attr}()`")
            return False
        if name in _NP_TRANSFER and any_tainted:
            leak(f"`{name}(...)`")
            return False
        # interprocedural step through a resolved in-package call
        target = resolved.get((node.lineno, node.col_offset))
        if target is not None and any_tainted:
            callee = self.pkg.functions.get(target)
            if callee is not None:
                params = [a.arg for a in callee.node.args.args]
                skip_self = bool(params) and params[0] in ("self", "cls")
                if skip_self and isinstance(node.func, ast.Attribute):
                    params = params[1:]
                sub: Set[str] = set()
                for i, t in enumerate(arg_taints):
                    if t and i < len(params):
                        sub.add(params[i])
                for k, t in kw_taints.items():
                    if t and k in params:
                        sub.add(k)
                if sub:
                    item = (target, frozenset(sub))
                    if item not in self.done:
                        self.done.add(item)
                        self.parents[item] = key
                        self.queue.append(item)
        # a receiver-method call on a traced value stays traced
        if isinstance(node.func, ast.Attribute) and tainted(
            node.func.value
        ):
            return True
        return any_tainted


class _Findings:
    def __init__(self) -> None:
        self.seen: Set[Tuple[str, str, int]] = set()
        self.violations: List[Violation] = []

    def add(
        self, rule: str, path: str, lineno: int, message: str, source: str
    ) -> None:
        key = (rule, path, lineno)
        if key in self.seen:
            return
        self.seen.add(key)
        self.violations.append(
            Violation(
                rule=rule,
                path=path,
                line=lineno,
                col=0,
                message=message,
                source=source,
            )
        )


def tracer_leak_violations(
    pkg: Package, roots: List[JitRoot]
) -> List[Violation]:
    """Interprocedural tracer-leak findings over the traced region."""
    report = _Findings()
    tp = _TaintPass(pkg, report)
    for root in roots:
        if root.target_key is None:
            continue
        fi = pkg.functions.get(root.target_key)
        if fi is None:
            continue
        params = _array_params(fi, root)
        if params:
            tp.seed(root.target_key, params)
    tp.run()
    report.violations.sort(key=lambda v: (v.path, v.line))
    return report.violations


# ---------------------------------------------------------------------------
# pass 2: dev-host-sync (migrated from tmlint, legacy dispatch scope)


def host_sync_violations(pkg: Package) -> List[Violation]:
    rule = DevHostSync()
    out: List[Violation] = []
    for path in sorted(pkg.modules):
        if not (
            path in LEGACY_DEVICE_FILES
            or path.startswith(LEGACY_DEVICE_PREFIXES)
        ):
            continue
        mod = LintModule(path, pkg.modules[path].source)
        for v in rule.check(mod):
            if not mod.is_suppressed(v.rule, v.line):
                out.append(v)
    return out


# ---------------------------------------------------------------------------
# pass 3: dev-shape-leak, widened with bucket-provenance dataflow

S, U, D = "static", "unknown", "dynamic"


def _meet(*classes: str) -> str:
    if D in classes:
        return D
    if U in classes:
        return U
    return S


class _Provenance:
    """Three-valued shape provenance over the dispatch scope."""

    def __init__(self, pkg: Package) -> None:
        self.pkg = pkg
        # (path, qualname, param) -> class; top (static) until a
        # resolved call site lowers it
        self.params: Dict[Tuple[str, str, str], str] = {}

    def param_class(self, fi: FuncInfo, name: str) -> str:
        return self.params.get((fi.path, fi.qualname, name), S)

    def classify(
        self, node: ast.AST, ctx: Dict[str, str], fi: Optional[FuncInfo]
    ) -> str:
        if isinstance(node, ast.Constant):
            return S
        if isinstance(node, ast.Name):
            if node.id in ctx:
                return ctx[node.id]
            if fi is not None and node.id in {
                a.arg for a in fi.node.args.args
            }:
                return self.param_class(fi, node.id)
            if node.id == node.id.upper():
                return S
            return U
        if isinstance(node, ast.Attribute):
            return S  # self.BUCKET / cls.SIZE / F.NLIMBS: configuration
        if isinstance(node, ast.Subscript):
            if (
                isinstance(node.value, ast.Attribute)
                and node.value.attr == "shape"
            ):
                return S  # x.shape[i] is concrete during tracing
            return self.classify(node.value, ctx, fi)
        if isinstance(node, (ast.Tuple, ast.List)):
            return _meet(
                *(self.classify(e, ctx, fi) for e in node.elts)
            ) if node.elts else S
        if isinstance(node, ast.BinOp):
            return _meet(
                self.classify(node.left, ctx, fi),
                self.classify(node.right, ctx, fi),
            )
        if isinstance(node, ast.UnaryOp):
            return self.classify(node.operand, ctx, fi)
        if isinstance(node, ast.IfExp):
            return _meet(
                self.classify(node.body, ctx, fi),
                self.classify(node.orelse, ctx, fi),
            )
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            last = name.split(".")[-1] if name else ""
            if last in _BUCKETIZERS or last == "_bucket":
                return S  # the pad-bucket table: dynamic in, bucket out
            if last == "len":
                return D
            if last in ("min", "max", "abs", "sum"):
                return _meet(
                    *(self.classify(a, ctx, fi) for a in node.args)
                ) if node.args else U
            return U
        return U

    def build_ctx(
        self, body: Iterable[ast.stmt], fi: Optional[FuncInfo]
    ) -> Dict[str, str]:
        """One forward pass over a statement list (program order,
        loops not iterated — provenance only ever *lowers*, so a
        single pass is sound for flagging purposes)."""
        ctx: Dict[str, str] = {}

        def bind(tgt: ast.AST, cls: str, value: ast.AST = None) -> None:
            if isinstance(tgt, ast.Name):
                ctx[tgt.id] = cls
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                # `length, n = data.shape` unpacks static dims
                if (
                    value is not None
                    and isinstance(value, ast.Attribute)
                    and value.attr == "shape"
                ):
                    for e in tgt.elts:
                        bind(e, S)
                    return
                elts = (
                    value.elts
                    if isinstance(value, (ast.Tuple, ast.List))
                    and len(value.elts) == len(tgt.elts)
                    else None
                )
                for i, e in enumerate(tgt.elts):
                    if elts is not None:
                        bind(e, self.classify(elts[i], ctx, fi))
                    else:
                        bind(e, cls)

        def walk(stmts) -> None:
            for st in stmts:
                if isinstance(st, ast.Assign):
                    cls = self.classify(st.value, ctx, fi)
                    for tgt in st.targets:
                        bind(tgt, cls, st.value)
                elif isinstance(st, ast.AugAssign) and isinstance(
                    st.target, ast.Name
                ):
                    ctx[st.target.id] = _meet(
                        ctx.get(st.target.id, U),
                        self.classify(st.value, ctx, fi),
                    )
                elif isinstance(st, ast.AnnAssign) and st.value is not None:
                    bind(st.target, self.classify(st.value, ctx, fi))
                elif isinstance(st, (ast.For, ast.AsyncFor)):
                    bind(st.target, U)
                    walk(st.body)
                    walk(st.orelse)
                elif isinstance(st, (ast.If, ast.While)):
                    walk(st.body)
                    walk(st.orelse)
                elif isinstance(st, (ast.With, ast.AsyncWith)):
                    walk(st.body)
                elif isinstance(st, ast.Try):
                    walk(st.body)
                    for h in st.handlers:
                        walk(h.body)
                    walk(st.orelse)
                    walk(st.finalbody)

        walk(list(body))
        return ctx

    def solve_params(self, scope_paths: Set[str]) -> None:
        """Meet every scoped function's param provenance over its
        resolved call sites (3 rounds bound the descending chain
        static > unknown > dynamic)."""
        for _ in range(3):
            changed = False
            for fi in self.pkg.functions.values():
                mod = self.pkg.modules.get(fi.path)
                if mod is None:
                    continue
                resolved = {
                    (s.lineno, s.col): s.target
                    for s in fi.calls
                    if s.target is not None
                }
                ctx = self.build_ctx(fi.node.body, fi)
                for node in _body_walk(fi.node):
                    if not isinstance(node, ast.Call):
                        continue
                    target = resolved.get(
                        (node.lineno, node.col_offset)
                    )
                    if target is None or target[0] not in scope_paths:
                        continue
                    callee = self.pkg.functions.get(target)
                    if callee is None:
                        continue
                    params = [a.arg for a in callee.node.args.args]
                    if params and params[0] in ("self", "cls") and (
                        isinstance(node.func, ast.Attribute)
                    ):
                        params = params[1:]
                    for i, a in enumerate(node.args):
                        if i >= len(params):
                            break
                        cls = self.classify(a, ctx, fi)
                        k = (target[0], target[1], params[i])
                        old = self.params.get(k, S)
                        new = _meet(old, cls)
                        if new != old:
                            self.params[k] = new
                            changed = True
                    for kw in node.keywords:
                        if kw.arg and kw.arg in params:
                            cls = self.classify(kw.value, ctx, fi)
                            k = (target[0], target[1], kw.arg)
                            old = self.params.get(k, S)
                            new = _meet(old, cls)
                            if new != old:
                                self.params[k] = new
                                changed = True
            if not changed:
                break


def shape_leak_violations(pkg: Package) -> List[Violation]:
    """dev-shape-leak over the widened dispatch scope (ops/ included)
    with the bucket-provenance dataflow."""
    scope = {p for p in pkg.modules if is_dispatch_scope(p)}
    prov = _Provenance(pkg)
    prov.solve_params(scope)
    out: List[Violation] = []
    for path in sorted(scope):
        mod = pkg.modules[path]
        lint_mod = LintModule(path, mod.source)
        # per-function sweep (plus module top level via fi=None)
        fns = [
            fi for fi in pkg.functions.values() if fi.path == path
        ]
        units: List[Tuple[Optional[FuncInfo], Iterable[ast.stmt]]] = [
            (fi, fi.node.body) for fi in fns
        ]
        units.append((None, mod.tree.body))
        for fi, body in units:
            ctx = prov.build_ctx(body, fi)
            nodes = (
                _body_walk(fi.node) if fi is not None else _toplevel(mod.tree)
            )
            for node in nodes:
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if name not in _JNP_SHAPED_CTORS or not node.args:
                    continue
                cls = prov.classify(node.args[0], ctx, fi)
                if cls == S:
                    continue
                if lint_mod.is_suppressed("dev-shape-leak", node.lineno):
                    continue
                out.append(
                    Violation(
                        rule="dev-shape-leak",
                        path=path,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"`{name}` called with a {cls}-provenance "
                            f"shape (`{ast.unparse(node.args[0])}`); "
                            "every distinct value compiles a new XLA "
                            "program — derive the shape from the "
                            "pad-bucket table (bucket_for / "
                            "pallas_bucket / *._bucket) or a "
                            "configured constant"
                        ),
                        source=_line(pkg, path, node.lineno),
                    )
                )
    out.sort(key=lambda v: (v.path, v.line))
    return out


def _toplevel(tree: ast.Module):
    """Module-level statements only (function bodies are their own
    units)."""
    stack: List[ast.AST] = list(tree.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))
