"""Static discovery of every `jax.jit` root in the package.

A *jit root* is a source site that hands a function to `jax.jit`
(directly, via `functools.partial(jax.jit, ...)` as a decorator, or
as a plain `@jax.jit` decorator). Everything tmtrace proves — trace
stability, the signature budget, the no-TPU compile gate — is
quantified over this set, so discovery must be a whole-package AST
scan, not a hand-kept list: a new `jax.jit` anywhere in the package
is discovered on the next run and, lacking a shapemodel entry, fails
the gate as `trace-unknown-root` until its bucket shapes are
declared.

Each root records the jit *target* (resolved to an in-package
function where the receiver is static; `type(self)._TILE_FN`-style
dynamic targets keep their source text as identity), the declared
`static_argnames`/`static_argnums`, and any `donate_argnums`/
`donate_argnames` (consumed by shardcheck's donated-reuse rule).

The *traced region* — every in-package function reachable from a jit
target through the PR-5 call graph — is where a `.item()`, a
`float()`, or Python control flow on an abstract value is a trace
error rather than a slowdown; shapeflow runs its interprocedural
tracer-leak pass exactly there.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from ..tmcheck.callgraph import Package

__all__ = [
    "JitRoot",
    "DEVICE_MODULE_FILES",
    "DEVICE_MODULE_PREFIXES",
    "discover",
    "traced_region",
    "is_dispatch_scope",
]

FuncKey = Tuple[str, str]

# The dispatch half of the device scope: tmlint's historical device
# modules (crypto/batch.py, crypto/tpu_verifier.py, parallel/) plus
# ops/ — every module that either packs buckets for, or defines, a
# device program.
DEVICE_MODULE_FILES = {"crypto/batch.py", "crypto/tpu_verifier.py"}
DEVICE_MODULE_PREFIXES = ("parallel/", "ops/")


def is_dispatch_scope(path: str) -> bool:
    return path in DEVICE_MODULE_FILES or path.startswith(
        DEVICE_MODULE_PREFIXES
    )


class JitRoot:
    """One jax.jit site."""

    __slots__ = (
        "path",
        "lineno",
        "target_src",
        "target_key",
        "static_argnames",
        "static_argnums",
        "donate_argnums",
        "donate_argnames",
        "assigned_name",
    )

    def __init__(
        self,
        path: str,
        lineno: int,
        target_src: str,
        target_key: Optional[FuncKey],
        static_argnames: Tuple[str, ...] = (),
        static_argnums: Tuple[int, ...] = (),
        donate_argnums: Tuple[int, ...] = (),
        donate_argnames: Tuple[str, ...] = (),
        assigned_name: str = "",
    ) -> None:
        self.path = path
        self.lineno = lineno
        self.target_src = target_src
        self.target_key = target_key
        self.static_argnames = static_argnames
        self.static_argnums = static_argnums
        self.donate_argnums = donate_argnums
        self.donate_argnames = donate_argnames
        # local/module name the jitted callable is bound to at the
        # site (`fn = jax.jit(...)`) — shardcheck's donated-reuse
        # rule follows calls through it
        self.assigned_name = assigned_name

    @property
    def rid(self) -> str:
        """Stable identity: site module + the target expression's
        source text (line numbers deliberately do not participate)."""
        return f"{self.path}:{self.target_src}"

    def render(self) -> str:
        return f"{self.rid} (line {self.lineno})"


def _const_str_tuple(node: ast.AST) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
        return tuple(out)
    return ()


def _const_int_tuple(node: ast.AST) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return tuple(out)
    return ()


def _is_jax_jit(node: ast.AST, mod) -> bool:
    """`jax.jit` / `jit` (from-imported) / alias thereof."""
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        base = node.value
        if isinstance(base, ast.Name):
            alias = mod.import_alias.get(base.id, base.id)
            return alias in ("jax", "jax.numpy") or alias.startswith("jax")
        return False
    if isinstance(node, ast.Name):
        fi = mod.from_imports.get(node.id)
        return fi is not None and fi[1] == "jax" and fi[2] == "jit"
    return False


def _resolve_target(
    pkg: Package, mod, node: ast.AST
) -> Tuple[str, Optional[FuncKey]]:
    """(source text, in-package FuncInfo key or None) of a jit-target
    expression. Unwraps one functools.partial layer."""
    if isinstance(node, ast.Call):
        fname = ast.unparse(node.func)
        if fname.endswith("partial") and node.args:
            inner_src, inner_key = _resolve_target(pkg, mod, node.args[0])
            return ast.unparse(node), inner_key
        return ast.unparse(node), None
    src = ast.unparse(node)
    if isinstance(node, ast.Name):
        name = node.id
        if name in mod.functions:
            return src, (mod.path, name)
        fi = mod.from_imports.get(name)
        if fi is not None and fi[0] is not None:
            target = pkg.module_for_dotted(fi[0])
            if target is not None and fi[2] in target.functions:
                return src, (target.path, fi[2])
        return src, None
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        # S.inner_hash_batch through a module alias / from-import
        head = node.value.id
        target = None
        alias = mod.import_alias.get(head)
        if alias is not None:
            prefix = pkg.pkg_name + "."
            if alias.startswith(prefix):
                target = pkg.module_for_dotted(alias[len(prefix):])
        else:
            fi = mod.from_imports.get(head)
            if fi is not None and fi[0] is not None:
                base = fi[0] + "." + fi[2] if fi[0] else fi[2]
                target = pkg.module_for_dotted(base)
        if target is not None and node.attr in target.functions:
            return src, (target.path, node.attr)
    return src, None


def _root_from_jit_call(
    pkg: Package, mod, call: ast.Call, assigned_name: str = ""
) -> Optional[JitRoot]:
    if not call.args:
        return None
    target_src, target_key = _resolve_target(pkg, mod, call.args[0])
    kw = {k.arg: k.value for k in call.keywords if k.arg}
    return JitRoot(
        mod.path,
        call.lineno,
        target_src,
        target_key,
        static_argnames=_const_str_tuple(kw.get("static_argnames")),
        static_argnums=_const_int_tuple(kw.get("static_argnums")),
        donate_argnums=_const_int_tuple(kw.get("donate_argnums")),
        donate_argnames=_const_str_tuple(kw.get("donate_argnames")),
        assigned_name=assigned_name,
    )


def discover(pkg: Package) -> List[JitRoot]:
    """Every jax.jit site in the package, in (path, lineno) order."""
    roots: List[JitRoot] = []
    for path in sorted(pkg.modules):
        mod = pkg.modules[path]
        # decorators first: @jax.jit and
        # @functools.partial(jax.jit, static_argnames=...)
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _is_jax_jit(dec, mod):
                        roots.append(
                            JitRoot(
                                path,
                                dec.lineno,
                                node.name,
                                (path, node.name)
                                if (path, node.name) in pkg.functions
                                else None,
                            )
                        )
                    elif (
                        isinstance(dec, ast.Call)
                        and ast.unparse(dec.func).endswith("partial")
                        and dec.args
                        and _is_jax_jit(dec.args[0], mod)
                    ):
                        kw = {
                            k.arg: k.value for k in dec.keywords if k.arg
                        }
                        roots.append(
                            JitRoot(
                                path,
                                dec.lineno,
                                node.name,
                                (path, node.name)
                                if (path, node.name) in pkg.functions
                                else None,
                                static_argnames=_const_str_tuple(
                                    kw.get("static_argnames")
                                ),
                                static_argnums=_const_int_tuple(
                                    kw.get("static_argnums")
                                ),
                                donate_argnums=_const_int_tuple(
                                    kw.get("donate_argnums")
                                ),
                                donate_argnames=_const_str_tuple(
                                    kw.get("donate_argnames")
                                ),
                            )
                        )
            elif isinstance(node, ast.Call) and _is_jax_jit(
                node.func, mod
            ):
                assigned = ""
                # `X = jax.jit(...)`: remember the bound name so the
                # donated-reuse rule can follow calls through it
                parent_assign = None
                # cheap parent scan: jit calls are rare, so a local
                # walk per site beats building parent links
                for cand in ast.walk(mod.tree):
                    if (
                        isinstance(cand, ast.Assign)
                        and cand.value is node
                        and len(cand.targets) == 1
                        and isinstance(cand.targets[0], ast.Name)
                    ):
                        parent_assign = cand.targets[0].id
                        break
                if parent_assign:
                    assigned = parent_assign
                root = _root_from_jit_call(pkg, mod, node, assigned)
                if root is not None:
                    roots.append(root)
    roots.sort(key=lambda r: (r.path, r.lineno))
    return roots


def traced_region(
    pkg: Package, roots: List[JitRoot]
) -> Set[FuncKey]:
    """Every function reachable from a jit target through the call
    graph (witness chains for findings come from the taint pass's own
    parent links, which also carry the tainted-param mask)."""
    region: Set[FuncKey] = set()
    queue: List[FuncKey] = []
    for r in roots:
        if r.target_key is not None and r.target_key in pkg.functions:
            if r.target_key not in region:
                region.add(r.target_key)
                queue.append(r.target_key)
    while queue:
        key = queue.pop()
        for site in pkg.functions[key].calls:
            tgt = site.target
            if tgt is not None and tgt not in region:
                region.add(tgt)
                queue.append(tgt)
    return region
