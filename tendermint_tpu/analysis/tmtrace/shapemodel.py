"""The declared shape table: every jit root's bucket signatures.

The recompile budget is a *number*: each (jit root, shape, dtype,
static-args) signature XLA has to compile exactly once, and an
accidental new signature is a silent mid-round recompilation storm on
the hot path (dev-shape-leak's rationale, made whole-program). This
module declares, for every discovered jit root, how its input shapes
are generated from the pad-bucket configuration — and enumerates the
resulting signature set from the LIVE config
(`config.DEFAULT_BUCKET_SIZES`, `pallas_bucket`, `TILE`,
`field25519.NLIMBS`), so editing any of those regenerates a different
set and fails the drift gate until `scripts/lint.py
--signatures-update` re-accepts it.

Three signature families:

- *bucketed*: concrete per-bucket avals (the ed25519/sr25519 tiles,
  sha512 with its symbolic message-length dimension `M`),
- *power-of-two*: merkle's `_bucket` (next pow2 ≥ n, min 8) yields an
  unbounded but structured family, recorded symbolically,
- *mesh-sharded*: parallel/sharding.py's per-mesh programs, recorded
  as the round-up formula over the base bucket table (the live
  divisibility gate proves the formula; the underlying tile body
  signatures are the ed25519/sr25519 entries).

A discovered root with no entry here is `trace-unknown-root` — the
author of a new `jax.jit` must declare its shape family before the
gate passes, which is exactly the review conversation the rule
exists to force.

Trace cases: each entry also says how to build concrete
(fn, avals) pairs for the no-TPU compile gate. `cost="fast"` cases
(sha256/sha512/merkle — <0.5 s each) run in the default tier-1 gate;
`cost="heavy"` cases (the crypto tiles and Pallas kernels, ~6-8 s of
tracing EACH) run only in the full sweep
(`scripts/lint.py --trace-full`, timed by bench.py's
`trace_all_buckets` row as the device-campaign pre-flight cost).
The heavy tiles are still traced on every tier-1 run — by the
differential tests (tests/test_ops_ed25519.py, test_ops_pallas.py),
which execute them at small shapes — so the default gate skipping
them costs no coverage, only the per-bucket enumeration.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..tmlint import Violation

__all__ = [
    "GOLDEN_PATH",
    "MODEL",
    "REP_MSG_LEN",
    "TraceCase",
    "model_signatures",
    "current_table",
    "drift_violations",
    "load_golden",
    "save_golden",
    "trace_cases",
]

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "jit_signatures.json")

# representative sign-bytes length for sha512's symbolic M dimension:
# a canonical vote for a ~12-byte chain id (the shape the commit path
# hashes all day). Concrete only for the live trace; the golden
# signature keeps M symbolic so chain-id length never fails the gate.
REP_MSG_LEN = 110


def _buckets() -> Tuple[int, ...]:
    from ...config import DEFAULT_BUCKET_SIZES

    return tuple(DEFAULT_BUCKET_SIZES)


def _pallas_buckets() -> Tuple[int, ...]:
    from ...ops.ed25519_kernel import pallas_bucket

    return tuple(sorted({pallas_bucket(b) for b in _buckets()}))


def _all_tile_buckets() -> Tuple[int, ...]:
    # the XLA tile serves both the plain bucket table and, through
    # run_with_pallas_fallback, the pallas-rounded buckets
    return tuple(sorted(set(_buckets()) | set(_pallas_buckets())))


def _nlimbs() -> int:
    from ...ops import field25519 as F

    return F.NLIMBS


class TraceCase:
    """One concrete eval_shape case for the compile gate."""

    __slots__ = ("rid", "label", "cost", "build")

    def __init__(
        self, rid: str, label: str, cost: str, build: Callable
    ) -> None:
        self.rid = rid
        self.label = label
        self.cost = cost
        self.build = build  # () -> (fn, avals tuple)


class RootModel:
    __slots__ = ("rid", "cost", "signatures_fn", "cases_fn")

    def __init__(
        self,
        rid: str,
        cost: str,
        signatures_fn: Callable[[], List[str]],
        cases_fn: Callable[[bool], List[TraceCase]],
    ) -> None:
        self.rid = rid
        self.cost = cost
        self.signatures_fn = signatures_fn
        self.cases_fn = cases_fn


def _avals(*specs):
    import jax
    import jax.numpy as jnp

    dt = {"i32": jnp.int32, "u8": jnp.uint8}
    return tuple(
        jax.ShapeDtypeStruct(shape, dt[d]) for shape, d in specs
    )


# -- per-root case builders (lazy imports keep the static passes
# jax-free until a live trace is actually requested) --


def _ed_tile_case(b: int) -> TraceCase:
    def build():
        from ...ops.ed25519_kernel import _verify_tile

        return _verify_tile, _avals(
            ((32, b), "i32"), ((64, b), "i32"), ((64, b), "i32")
        )

    return TraceCase(
        "ops/ed25519_kernel.py:_verify_tile",
        f"ed25519_tile@{b}",
        "heavy",
        build,
    )


def _sr_tile_case(b: int, hybrid: bool) -> TraceCase:
    def build():
        from ...ops.sr25519_kernel import _verify_tile_sr

        if hybrid:
            import functools

            from ...ops.ed25519_pallas import dual_mult_pallas

            fn = functools.partial(
                _verify_tile_sr, dual_fn=dual_mult_pallas
            )
        else:
            fn = _verify_tile_sr
        return fn, _avals(
            ((32, b), "i32"), ((64, b), "i32"), ((32, b), "i32")
        )

    rid = (
        "ops/sr25519_kernel.py:functools.partial(_verify_tile_sr, "
        "dual_fn=dual_mult_pallas)"
        if hybrid
        else "ops/sr25519_kernel.py:_verify_tile_sr"
    )
    return TraceCase(
        rid,
        f"sr25519_{'hybrid' if hybrid else 'tile'}@{b}",
        "heavy",
        build,
    )


def _sha512_case(b: int, mlen: int) -> TraceCase:
    def build():
        from ...ops.sha512_kernel import sha512_fixed

        return sha512_fixed, _avals(((64 + mlen, b), "u8"))

    return TraceCase(
        "ops/ed25519_kernel.py:sha512_fixed",
        f"sha512@M{mlen}x{b}",
        "fast",
        build,
    )


def _inner_hash_case(b: int) -> TraceCase:
    def build():
        from ...ops.sha256_kernel import inner_hash_batch

        return inner_hash_batch, _avals(
            ((32, b), "u8"), ((32, b), "u8")
        )

    return TraceCase(
        "ops/merkle_kernel.py:S.inner_hash_batch",
        f"merkle_inner@{b}",
        "fast",
        build,
    )


def _merkle_proof_case(k: int, d: int) -> TraceCase:
    def build():
        from ...ops.merkle_kernel import _verify_program

        return _verify_program, _avals(
            ((32, k), "u8"), ((d, 32, k), "u8"), ((d, k), "i32")
        )

    return TraceCase(
        "ops/merkle_kernel.py:_verify_program",
        f"merkle_proofs@k{k}d{d}",
        "fast",
        build,
    )


def _pallas_case(kind: str, b: int) -> TraceCase:
    def build():
        import functools

        from ...ops import ed25519_pallas as P

        fn = getattr(P, kind)
        fn = functools.partial(fn, interpret=False, tile=P.TILE)
        L = _nlimbs()
        if kind == "dual_mult_pallas":
            avals = _avals(
                ((4, L, b), "i32"), ((64, b), "i32"), ((64, b), "i32")
            )
        else:
            avals = _avals(
                ((32, b), "i32"), ((64, b), "i32"), ((64, b), "i32")
            )
        return fn, avals

    return TraceCase(
        f"ops/ed25519_pallas.py:{kind}",
        f"{kind}@{b}",
        "heavy",
        build,
    )


def _sig(shapes_dtypes: Sequence[Tuple[str, str]]) -> str:
    return ",".join(f"{d}[{s}]" for s, d in shapes_dtypes)


def _build_model() -> Dict[str, RootModel]:
    model: Dict[str, RootModel] = {}

    def add(rid, cost, sigs, cases):
        model[rid] = RootModel(rid, cost, sigs, cases)

    add(
        "ops/ed25519_kernel.py:_verify_tile",
        "heavy",
        lambda: [
            _sig([(f"32,{b}", "i32"), (f"64,{b}", "i32"), (f"64,{b}", "i32")])
            for b in _all_tile_buckets()
        ],
        lambda full: [_ed_tile_case(b) for b in _all_tile_buckets()]
        if full
        else [],
    )
    add(
        "ops/ed25519_kernel.py:sha512_fixed",
        "fast",
        lambda: [
            _sig([(f"64+M,{b}", "u8")]) + " M∈msg-len"
            for b in _buckets()
        ],
        lambda full: [
            _sha512_case(b, REP_MSG_LEN)
            for b in (
                _buckets()
                if full
                else (min(_buckets()), max(_buckets()))
            )
        ],
    )
    add(
        "ops/sr25519_kernel.py:_verify_tile_sr",
        "heavy",
        lambda: [
            _sig([(f"32,{b}", "i32"), (f"64,{b}", "i32"), (f"32,{b}", "i32")])
            for b in _all_tile_buckets()
        ],
        lambda full: [
            _sr_tile_case(b, hybrid=False) for b in _all_tile_buckets()
        ]
        if full
        else [],
    )
    add(
        "ops/sr25519_kernel.py:functools.partial(_verify_tile_sr, "
        "dual_fn=dual_mult_pallas)",
        "heavy",
        lambda: [
            _sig([(f"32,{b}", "i32"), (f"64,{b}", "i32"), (f"32,{b}", "i32")])
            + " (pallas dual-mult segment)"
            for b in _pallas_buckets()
        ],
        lambda full: [
            _sr_tile_case(b, hybrid=True) for b in _pallas_buckets()
        ]
        if full
        else [],
    )
    add(
        "ops/merkle_kernel.py:S.inner_hash_batch",
        "fast",
        lambda: ["u8[32,2^k],u8[32,2^k] k>=3 (pow2 buckets, min 8)"],
        lambda full: [
            _inner_hash_case(b) for b in ((8, 1024) if full else (8,))
        ],
    )
    add(
        "ops/merkle_kernel.py:_verify_program",
        "fast",
        lambda: [
            "u8[32,2^k],u8[2^d,32,2^k],i32[2^d,2^k] "
            "(pow2 batch and proof depth, min 8)"
        ],
        lambda full: [
            _merkle_proof_case(k, d)
            for k, d in (((8, 8), (64, 16)) if full else ((8, 8),))
        ],
    )
    for kind in ("verify_pallas", "dual_mult_pallas", "verify_hybrid"):
        add(
            f"ops/ed25519_pallas.py:{kind}",
            "heavy",
            (
                lambda kind=kind: [
                    (
                        _sig(
                            [
                                (f"4,{_nlimbs()},{b}", "i32"),
                                (f"64,{b}", "i32"),
                                (f"64,{b}", "i32"),
                            ]
                        )
                        if kind == "dual_mult_pallas"
                        else _sig(
                            [
                                (f"32,{b}", "i32"),
                                (f"64,{b}", "i32"),
                                (f"64,{b}", "i32"),
                            ]
                        )
                    )
                    + " static:(interpret=False,tile=128)"
                    for b in _pallas_buckets()
                ]
            ),
            (
                lambda full, kind=kind: [
                    _pallas_case(kind, b)
                    for b in (
                        _pallas_buckets()
                        if full
                        else ()
                    )
                ]
            ),
        )
    add(
        "parallel/sharding.py:type(self)._TILE_FN",
        "heavy",
        lambda: [
            f"sharded(sig axis): base bucket {b} -> "
            "roundup(b, mesh) per mesh size"
            for b in _buckets()
        ],
        # no direct trace: the tile bodies are the ed25519/sr25519
        # entries; mesh placement is proven by the divisibility gate
        lambda full: [],
    )
    return model


MODEL: Dict[str, RootModel] = _build_model()


def model_signatures() -> Dict[str, List[str]]:
    return {rid: m.signatures_fn() for rid, m in MODEL.items()}


def trace_cases(full: bool) -> List[TraceCase]:
    out: List[TraceCase] = []
    for m in MODEL.values():
        out.extend(m.cases_fn(full))
    return out


# ---------------------------------------------------------------------------
# golden table


def load_golden(path: Optional[str] = None) -> Optional[dict]:
    path = path or GOLDEN_PATH
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def current_table(roots) -> dict:
    """The live (root -> signature record) table: discovery provides
    the root set and static/donate declarations, the model provides
    the enumerated signatures."""
    sigs = model_signatures()
    table: Dict[str, dict] = {}
    for r in roots:
        rec = {
            "signatures": sigs.get(r.rid, []),
            "static_argnames": sorted(r.static_argnames),
            "static_argnums": sorted(r.static_argnums),
            "donates": bool(r.donate_argnums or r.donate_argnames),
        }
        table[r.rid] = rec
    return table


def save_golden(roots, path: Optional[str] = None) -> dict:
    path = path or GOLDEN_PATH
    data = {
        "version": 1,
        "generated_by": "scripts/lint.py --signatures-update",
        "note": (
            "Golden jit-signature table: every jax.jit root in the "
            "package and the full (bucket shape, dtype, static-arg) "
            "signature set its pad-bucket family compiles, enumerated "
            "from the live config by analysis/tmtrace/shapemodel.py. "
            "Any drift — a new root, a removed root, a new bucket, a "
            "changed static arg — fails tier-1 until reviewed and "
            "re-accepted with scripts/lint.py --signatures-update. "
            "Do not hand-edit."
        ),
        "roots": {
            rid: rec for rid, rec in sorted(current_table(roots).items())
        },
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=False)
        f.write("\n")
    return data


def drift_violations(
    roots, golden: Optional[dict], pkg=None
) -> List[Violation]:
    """trace-unknown-root (no model entry) + trace-signature-drift
    (current enumeration vs golden)."""
    out: List[Violation] = []
    by_rid = {r.rid: r for r in roots}

    def src_line(r):
        if pkg is None:
            return ""
        lines = pkg.modules[r.path].lines
        return (
            lines[r.lineno - 1].strip() if r.lineno <= len(lines) else ""
        )

    for r in roots:
        if r.rid not in MODEL:
            out.append(
                Violation(
                    rule="trace-unknown-root",
                    path=r.path,
                    line=r.lineno,
                    col=0,
                    message=(
                        f"jax.jit root `{r.target_src}` has no entry "
                        "in analysis/tmtrace/shapemodel.py — declare "
                        "its bucket-shape family (and re-run "
                        "scripts/lint.py --signatures-update) so the "
                        "recompile budget stays enumerable"
                    ),
                    source=src_line(r),
                )
            )
    current = current_table(roots)
    gold_roots = (golden or {}).get("roots", {})
    for rid, rec in sorted(current.items()):
        if rid not in MODEL:
            continue  # already reported as trace-unknown-root
        if rid not in gold_roots:
            r = by_rid[rid]
            out.append(
                Violation(
                    rule="trace-signature-drift",
                    path=r.path,
                    line=r.lineno,
                    col=0,
                    message=(
                        f"jit root `{rid}` is not in the golden "
                        "jit_signatures.json — a new signature family "
                        "(= new compilations on the hot path); review "
                        "and accept with scripts/lint.py "
                        "--signatures-update"
                    ),
                    source=src_line(r),
                )
            )
            continue
        g = gold_roots[rid]
        for field in (
            "signatures",
            "static_argnames",
            "static_argnums",
            "donates",
        ):
            if rec.get(field) != g.get(field):
                r = by_rid[rid]
                out.append(
                    Violation(
                        rule="trace-signature-drift",
                        path=r.path,
                        line=r.lineno,
                        col=0,
                        message=(
                            f"jit root `{rid}`: {field} drifted from "
                            f"the golden table (now {rec.get(field)!r}, "
                            f"golden {g.get(field)!r}) — an accidental "
                            "new bucket/static-arg is a silent "
                            "recompilation on the hot path; review "
                            "and re-accept with scripts/lint.py "
                            "--signatures-update"
                        ),
                        source=src_line(r),
                    )
                )
                break
    for rid in sorted(gold_roots):
        if rid not in current:
            path = rid.split(":", 1)[0]
            out.append(
                Violation(
                    rule="trace-signature-drift",
                    path=path,
                    line=1,
                    col=0,
                    message=(
                        f"golden jit root `{rid}` no longer exists in "
                        "the package — if the program was deliberately "
                        "removed, re-accept with scripts/lint.py "
                        "--signatures-update"
                    ),
                    source="",
                )
            )
    return out
