"""Sharding-consistency checks: mesh axes, bucket divisibility,
donated buffers.

The sharded dispatch path (parallel/sharding.py) partitions the
bucketed device programs over a 1-D `sig` mesh. Three properties die
silently if an edit breaks them, and each only detonates once a
multi-chip claim is finally granted — so they are gates here:

- **trace-mesh-axis** (static): every axis name appearing in a
  `PartitionSpec(...)` must be declared by some `Mesh(..., (<axes>,))`
  in the package. An undeclared axis raises at dispatch time on the
  first sharded call — i.e. mid-claim. Axis names are resolved
  through module-level string constants (`SIG_AXIS = "sig"`), the
  import aliases `P`/`PartitionSpec`, and constant tuples.

- **trace-bucket-indivisible** (live, run by tracegate): for every
  virtual mesh size 1..8, the *real* sharded verifier classes are
  instantiated against a duck-typed mesh and every bucket they would
  dispatch must divide by the mesh size — the property
  `_MeshSharded.__init__`/`_bucket` exists to guarantee, checked
  against the production rounding code rather than a re-derived
  formula, so a refactor that drops the round-up turns the gate red.

- **trace-donated-reuse** (static): a buffer donated to a jit program
  (`donate_argnums`/`donate_argnames`) is invalidated by dispatch;
  any later read of the same name in the enclosing scope is a
  use-after-donate that XLA only reports (as a cryptic
  "buffer donated" error) on the device. No in-tree site donates
  today; the rule exists so the first one that does is born checked
  (seeded fixture in tests/data/trace/).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..tmlint import Violation, dotted_name
from ..tmcheck.callgraph import Package
from .jitroots import JitRoot

__all__ = [
    "mesh_axis_violations",
    "donated_reuse_violations",
    "divisibility_violations",
    "MESH_SIZES",
]

# virtual mesh widths the divisibility gate proves (SHARD_SCALING.json
# measured divide-by-n to 8 virtual devices; 3 catches non-power-of-2)
MESH_SIZES = (1, 2, 3, 4, 8)


def _str_const(
    node: ast.AST, consts: Dict[str, str]
) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def _module_str_consts(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            out[node.targets[0].id] = node.value.value
    return out


def _pspec_names(mod) -> Set[str]:
    """Local names bound to jax.sharding.PartitionSpec (incl. the
    conventional `as P`)."""
    names = set()
    for local, (tgt, ext, orig) in mod.from_imports.items():
        if ext is not None and "sharding" in ext and orig == "PartitionSpec":
            names.add(local)
    return names


def mesh_axis_violations(pkg: Package) -> List[Violation]:
    """Every PartitionSpec axis must exist in a declared Mesh."""
    declared: Set[str] = set()
    uses: List[Tuple[str, int, str]] = []  # (path, lineno, axis)
    for path in sorted(pkg.modules):
        mod = pkg.modules[path]
        consts = _module_str_consts(mod.tree)
        pspec_locals = _pspec_names(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            last = name.split(".")[-1] if name else ""
            if last == "Mesh":
                axes_node = None
                if len(node.args) >= 2:
                    axes_node = node.args[1]
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        axes_node = kw.value
                if axes_node is not None:
                    if isinstance(axes_node, (ast.Tuple, ast.List)):
                        for e in axes_node.elts:
                            s = _str_const(e, consts)
                            if s:
                                declared.add(s)
                    else:
                        s = _str_const(axes_node, consts)
                        if s:
                            declared.add(s)
            elif (
                (isinstance(node.func, ast.Name) and last in pspec_locals)
                or name
                in ("jax.sharding.PartitionSpec", "sharding.PartitionSpec")
            ):
                for e in node.args:
                    s = _str_const(e, consts)
                    if s is not None:
                        uses.append((path, node.lineno, s))
    out: List[Violation] = []
    for path, lineno, axis in uses:
        if axis in declared:
            continue
        lines = pkg.modules[path].lines
        src = lines[lineno - 1].strip() if lineno <= len(lines) else ""
        out.append(
            Violation(
                rule="trace-mesh-axis",
                path=path,
                line=lineno,
                col=0,
                message=(
                    f"PartitionSpec axis '{axis}' is not declared by "
                    f"any Mesh in the package (declared: "
                    f"{sorted(declared) or 'none'}) — dispatch would "
                    "raise on the first sharded call, i.e. mid-claim"
                ),
                source=src,
            )
        )
    return out


def donated_reuse_violations(
    pkg: Package, roots: List[JitRoot]
) -> List[Violation]:
    """Reads of a donated buffer after the dispatch that consumed it."""
    out: List[Violation] = []
    donating = {
        (r.path, r.assigned_name): r
        for r in roots
        if r.assigned_name and (r.donate_argnums or r.donate_argnames)
    }
    if not donating:
        return out
    for fi in pkg.functions.values():
        root_names = {
            name: r
            for (p, name), r in donating.items()
            if p == fi.path
        }
        if not root_names:
            continue
        # find calls through the donating jitted name; map donated
        # positions/names to plain-Name args; flag later loads
        donated: List[Tuple[str, int, JitRoot]] = []  # (var, call line)
        for node in ast.walk(fi.node):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in root_names
            ):
                r = root_names[node.func.id]
                for i in r.donate_argnums:
                    if i < len(node.args) and isinstance(
                        node.args[i], ast.Name
                    ):
                        donated.append(
                            (node.args[i].id, node.lineno, r)
                        )
                for kw in node.keywords:
                    if (
                        kw.arg in r.donate_argnames
                        and isinstance(kw.value, ast.Name)
                    ):
                        donated.append((kw.value.id, node.lineno, r))
        for var, call_line, r in donated:
            for node in ast.walk(fi.node):
                if (
                    isinstance(node, ast.Name)
                    and node.id == var
                    and isinstance(node.ctx, ast.Load)
                    and node.lineno > call_line
                ):
                    lines = pkg.modules[fi.path].lines
                    src = (
                        lines[node.lineno - 1].strip()
                        if node.lineno <= len(lines)
                        else ""
                    )
                    out.append(
                        Violation(
                            rule="trace-donated-reuse",
                            path=fi.path,
                            line=node.lineno,
                            col=node.col_offset,
                            message=(
                                f"`{var}` was donated to "
                                f"`{r.assigned_name}` (line {call_line}, "
                                f"{r.rid}) and its buffer is invalid "
                                "after dispatch; copy before donating "
                                "or drop the donation"
                            ),
                            source=src,
                        )
                    )
                    break
    out.sort(key=lambda v: (v.path, v.line))
    return out


def divisibility_violations(
    sharded_classes: Optional[Sequence] = None,
    mesh_sizes: Sequence[int] = MESH_SIZES,
    probe_sizes: Sequence[int] = (1, 5, 100, 9000, 20000),
) -> List[Violation]:
    """Instantiate each sharded verifier against duck meshes of every
    virtual width and prove every bucket it would dispatch divides by
    the mesh — exercising the REAL `_MeshSharded` rounding code, not a
    re-derivation of it. Needs jax importable (tracegate runs it)."""
    import numpy as np

    if sharded_classes is None:
        from ...parallel import sharding as sh

        sharded_classes = (
            sh.ShardedEd25519Verifier,
            sh.ShardedSr25519Verifier,
        )

    class _DuckMesh:
        def __init__(self, n: int) -> None:
            self.devices = np.empty((n,), dtype=object)

    out: List[Violation] = []
    for cls in sharded_classes:
        for n in mesh_sizes:
            try:
                v = cls(_DuckMesh(n))
            except Exception as e:
                out.append(
                    Violation(
                        rule="trace-bucket-indivisible",
                        path="parallel/sharding.py",
                        line=1,
                        col=0,
                        message=(
                            f"{cls.__name__} failed to instantiate "
                            f"against a {n}-device mesh: {e!r}"
                        ),
                        source="",
                    )
                )
                continue
            bad = [b for b in v.bucket_sizes if b % n]
            bad += [
                v._bucket(m)
                for m in probe_sizes
                if v._bucket(m) % n
            ]
            if bad:
                out.append(
                    Violation(
                        rule="trace-bucket-indivisible",
                        path="parallel/sharding.py",
                        line=1,
                        col=0,
                        message=(
                            f"{cls.__name__} on a {n}-device mesh "
                            f"produces bucket(s) {sorted(set(bad))} "
                            f"not divisible by {n} — XLA would pad "
                            "unevenly or reject the sharding at "
                            "dispatch time"
                        ),
                        source="",
                    )
                )
    return out
