"""The no-TPU trace-compilation gate.

Every device program must *trace* (build a jaxpr through abstract
evaluation) before it can compile, and every trace failure a device
campaign would hit is reproducible on CPU with `jax.eval_shape` — no
backend, no claim, no hour burned. This module drives eval_shape over
the shapemodel's concrete (root × bucket) cases and converts
exceptions into `trace-compile-fail` violations, plus the live
bucket-divisibility check (shardcheck) that needs the real sharded
classes importable.

Two tiers (rationale in shapemodel.py):

- default (tier-1, part of the <10 s budget): the fast family —
  sha512 at the min/max buckets, the merkle inner-hash and proof
  programs — everything that traces in under half a second. The
  heavy crypto tiles are skipped *with their names recorded in
  stats["skipped_heavy"]*, never silently; tier-1's differential
  tests trace them at small shapes anyway.

- full (`scripts/lint.py --trace-full`, bench.py `trace_all_buckets`):
  every declared root × bucket — ~6-8 s of pure tracing per crypto
  tile per bucket, minutes total. This IS the campaign pre-flight:
  run it (or read its freshest bench row) before `device_wait` gets a
  claim, so the granted hour starts at compilation, not at the first
  trace error. An optional budget stops the sweep late rather than
  hanging a bench run; whatever was skipped is listed in
  stats["skipped_budget"].

Stats also record jit-cache sizes for the long-lived jitted wrappers
(the per-instance compiled-program dicts plus `_cache_size()` where
the jax version exposes it) — the recompile budget's runtime
counterpart.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..tmlint import Violation
from . import shapemodel, shardcheck

__all__ = ["run", "run_cases", "jit_cache_stats"]


def run_cases(
    cases: Sequence[shapemodel.TraceCase],
    anchors: Optional[Dict[str, Tuple[str, int]]] = None,
    budget_s: Optional[float] = None,
) -> Tuple[List[Violation], dict]:
    """eval_shape every case; exceptions become trace-compile-fail.
    `anchors` maps rid -> (path, lineno) for violation placement."""
    import jax

    anchors = anchors or {}
    violations: List[Violation] = []
    per_case_ms: Dict[str, float] = {}
    skipped_budget: List[str] = []
    t0 = time.monotonic()
    for case in cases:
        if budget_s is not None and time.monotonic() - t0 > budget_s:
            skipped_budget.append(case.label)
            continue
        t1 = time.monotonic()
        try:
            fn, avals = case.build()
            jax.eval_shape(fn, *avals)
        except Exception as e:  # noqa: BLE001 — ANY trace failure is the finding
            path, lineno = anchors.get(case.rid, (case.rid.split(":", 1)[0], 1))
            msg = repr(e)
            if len(msg) > 300:
                msg = msg[:300] + "…"
            violations.append(
                Violation(
                    rule="trace-compile-fail",
                    path=path,
                    line=lineno,
                    col=0,
                    message=(
                        f"jit root `{case.rid}` fails to trace at "
                        f"{case.label}: {msg} — this is the error a "
                        "device claim would hit mid-campaign; fix it "
                        "on CPU first"
                    ),
                    source="",
                )
            )
        per_case_ms[case.label] = round(
            (time.monotonic() - t1) * 1e3, 1
        )
    stats = {
        "traced": len(per_case_ms),
        "per_case_ms": per_case_ms,
        "skipped_budget": skipped_budget,
        "total_s": round(time.monotonic() - t0, 3),
    }
    return violations, stats


def jit_cache_stats() -> dict:
    """Sizes of the process's long-lived compiled-program caches: the
    bucketed verifiers' per-instance dicts and the module-level jitted
    wrappers (where this jax exposes `_cache_size`). Read-only — never
    constructs a verifier that doesn't already exist."""
    out: dict = {}
    try:
        from ...ops import ed25519_kernel as K

        if K._DEFAULT is not None:
            out["ed25519_verifier_compiled"] = len(K._DEFAULT._compiled)
        for name in ("_JIT_VERIFY", "_JIT_SHA512"):
            fn = getattr(K, name, None)
            if fn is not None and hasattr(fn, "_cache_size"):
                out[f"ed25519{name}_cache"] = fn._cache_size()
    except Exception:
        pass
    try:
        from ...ops import sr25519_kernel as SR

        if SR._DEFAULT is not None:
            out["sr25519_verifier_compiled"] = len(SR._DEFAULT._compiled)
        fn = SR._JIT_VERIFY_SR
        if fn is not None and hasattr(fn, "_cache_size"):
            out["sr25519_jit_cache"] = fn._cache_size()
    except Exception:
        pass
    try:
        from ...ops import merkle_kernel as MK

        if hasattr(MK._inner_jit, "_cache_size"):
            out["merkle_inner_cache"] = MK._inner_jit._cache_size()
        if hasattr(MK._verify_program, "_cache_size"):
            out["merkle_proofs_cache"] = MK._verify_program._cache_size()
    except Exception:
        pass
    return out


def run(
    roots=None,
    full: bool = False,
    budget_s: Optional[float] = None,
    divisibility: bool = True,
) -> Tuple[List[Violation], dict]:
    """The live half of the tmtrace gate: eval_shape cases (fast tier
    or the full root × bucket sweep) + the real-class bucket
    divisibility proof. Returns (violations, stats)."""
    anchors = {}
    for r in roots or ():
        anchors.setdefault(r.rid, (r.path, r.lineno))
    cases = shapemodel.trace_cases(full)
    violations, stats = run_cases(cases, anchors, budget_s)
    stats["tier"] = "full" if full else "fast"
    stats["skipped_heavy"] = (
        []
        if full
        else sorted(
            {
                m.rid
                for m in shapemodel.MODEL.values()
                if m.cost == "heavy" and not m.cases_fn(False)
            }
        )
    )
    if divisibility:
        violations.extend(shardcheck.divisibility_violations())
    stats["jit_cache"] = jit_cache_stats()
    return violations, stats
