"""tmct — secret-flow / constant-time proof over the crypto plane.

The eleventh lint-gate section. Every prior gate proves properties of
code under *hostile input*; tmct proves properties of code holding
*secrets*: private-key bytes, signing nonces (RFC 6979 HMAC-DRBG
state, the sr25519 merlin witness scalar), and expanded-key
intermediates. Two things must never happen to them, and both are
whole-program dataflow properties, not local style:

**Timing** — secret-dependent control flow or memory addressing. A
branch on key bits, a loop bounded by a nonce, a table indexed by a
scalar window, an `==` that short-circuits at the first differing
byte, a two-arg `pow` whose bignum cost tracks the exponent: each one
modulates *observable duration* by secret content, and a remote
adversary integrates over many probes. Pure Python cannot be
cycle-constant; what the gate enforces is **structure, not cycles**
(docs/static_analysis.md): the trace *shape* — which statements run,
which indices are touched, where comparisons stop — must be
independent of secret values. Comparisons route through
`libs/ctutil.bytes_eq`; lookups use arithmetic-mask scans
(ed25519_math._comb_select, secp256k1._ct_select); exponent paths use
3-arg pow.

**Lifetime / exfiltration** — secrets reaching rendered text or
shared state: f-strings, repr/print/format, exception args, logging
calls, the telemetry plane (libs/{log,metrics,profiler,trace}), or
any PR-9-cataloged shared container (crypto/sigcache, module-global
memos/rings) where a value outlives the operation that needed it.

Sources are machine-derived (sources.py): the transitive PrivKey
subclass closure, its non-public instance attrs and ctor params,
PrivKey-typed annotations package-wide, and os.urandom births inside
crypto//privval/. The taint engine (secretflow.py) runs the tmsafe
worklist architecture over the PR-5 call graph: per-function parameter
joins, return summaries, dynamic class-attribute growth (storing a
secret into `self.x` re-analyzes the class), declassification only at
named published-output boundaries (sign/pub_key/address/verify_*/
bytes_eq).

Rules:

- `ct-secret-branch` — if/while/ternary/assert/comprehension
  condition, or a range() bound, derived from a secret.
- `ct-secret-index` — subscript whose index involves a secret.
- `ct-secret-compare` — ==/!=/in/not-in with a secret operand
  (`is None` is presence, not content, and stays clean).
- `ct-vartime-pow` — two-arg pow/** with a secret exponent.
- `ct-leak-telemetry` — secret into f-string/repr/print/format/
  exception args/logging/telemetry plane, plus dataclass secret-typed
  fields without field(repr=False) (the generated __repr__ leak).
- `ct-leak-lifetime` — secret argument into crypto/sigcache, or a
  secret stored into a module-global name/container.

Suppressions: `# tmct: ct-ok — why` on the line or comment block
above it. The reason is *mandatory* — a bare `ct-ok` does not parse —
because every sanctioned site is a human-reviewed claim (rejection
sampling on locally-generated entropy, a published boolean, a
range check whose failure is fatal anyway). Counted fingerprint
baseline `ct_baseline.json` ships — and is pinned by test — EMPTY:
the crypto plane starts clean and stays clean.

Run via `scripts/lint.py --ct` (in the default full gate).
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Set

from ..tmlint import (
    Violation,
    comment_cover_lines,
    load_baseline,
    new_violations,
    save_baseline,
)
from ..tmcheck.callgraph import Package, build_package
from . import secretflow, sources  # noqa: F401
from .secretflow import SecretEngine
from .sources import SecretCatalog, derive_catalog

__all__ = [
    "RULES",
    "CT_BASELINE_PATH",
    "CT_BASELINE_NOTE",
    "CtReport",
    "analyze",
    "ct_violations",
    "new_ct_violations",
    "update_ct_baseline",
    "suppressed_lines",
]

CT_BASELINE_PATH = os.path.join(
    os.path.dirname(__file__), "ct_baseline.json"
)

CT_BASELINE_NOTE = (
    "Accepted pre-existing secret-flow findings, fingerprinted by "
    "rule:path:sha1(source_line)[:12]. This ships empty and stays "
    "empty: the crypto plane has no tolerated timing or lifetime "
    "leaks. A new finding is fixed, or suppressed in-file with a "
    "justified '# tmct: ct-ok — why' — never baselined."
)

RULES = [
    (
        "ct-secret-branch",
        "control flow (if/while/ternary/assert/comprehension/range "
        "bound) conditioned on a secret-derived value",
    ),
    (
        "ct-secret-index",
        "subscript index derived from a secret — data-dependent "
        "memory access pattern",
    ),
    (
        "ct-secret-compare",
        "==/!=/in/not-in with a secret operand — short-circuits at "
        "the first differing byte; use libs/ctutil.bytes_eq",
    ),
    (
        "ct-vartime-pow",
        "two-arg pow/** with a secret exponent — value-dependent "
        "bignum work; the 3-arg modular form is sanctioned",
    ),
    (
        "ct-leak-telemetry",
        "secret reaching rendered text: f-string, repr/print/format, "
        "exception args, logging calls, the telemetry plane, or a "
        "dataclass __repr__ without field(repr=False)",
    ),
    (
        "ct-leak-lifetime",
        "secret reaching shared long-lived state: crypto/sigcache "
        "arguments, module-global names or containers",
    ),
]

# The reason is mandatory: a dash (em/en/double/single) followed by at
# least one non-space character. A bare `# tmct: ct-ok` does not count.
_SUPPRESS_RE = re.compile(
    r"#\s*tmct:\s*ct-ok\s*(?:—|–|--|-)\s*\S"
)


def suppressed_lines(lines: List[str]) -> Set[int]:
    """Covered line numbers for `# tmct: ct-ok — why` annotations
    (comment-block-above convention shared with the family). One
    annotation covers every tmct rule on the covered lines: the
    reviewed claim is about the *site*, not one rule id."""
    out: Set[int] = set()
    for i, text in enumerate(lines, start=1):
        if not _SUPPRESS_RE.search(text):
            continue
        out.update(comment_cover_lines(lines, i, text))
    return out


class CtReport:
    def __init__(self) -> None:
        self.catalog: Optional[SecretCatalog] = None
        self.findings: List[secretflow.Finding] = []
        self.violations: List[Violation] = []
        self.stats: Dict[str, int] = {}
        # (rule, path, line) dropped by an in-file suppression — the
        # head-catalog test pins this set
        self.suppressed: List[tuple] = []


def analyze(pkg: Optional[Package] = None) -> CtReport:
    pkg = pkg or build_package()
    report = CtReport()

    supp: Dict[str, Set[int]] = {}
    for path, mod in pkg.modules.items():
        covered = suppressed_lines(mod.lines)
        if covered:
            supp[path] = covered

    def is_suppressed(path: str, lineno: int) -> bool:
        return lineno in supp.get(path, ())

    def line_text(path: str, lineno: int) -> str:
        lines = pkg.modules[path].lines
        if 1 <= lineno <= len(lines):
            return lines[lineno - 1].strip()
        return ""

    cat = derive_catalog(pkg)
    report.catalog = cat
    engine = SecretEngine(pkg, cat)
    findings = engine.run()
    report.findings = findings

    violations: List[Violation] = []
    n_supp = 0
    for f in findings:
        if is_suppressed(f.path, f.lineno):
            n_supp += 1
            report.suppressed.append((f.rule, f.path, f.lineno))
            continue
        chain = engine.chain(f.key)
        witness = " -> ".join(chain)
        violations.append(
            Violation(
                rule=f.rule,
                path=f.path,
                line=f.lineno,
                col=f.col,
                message=f"{f.detail}; witness: {witness}",
                source=line_text(f.path, f.lineno),
            )
        )

    # class-shape findings (dataclass __repr__) come from the catalog,
    # not the dataflow engine
    for path, lineno, col, detail in cat.repr_leaks:
        if is_suppressed(path, lineno):
            n_supp += 1
            report.suppressed.append(("ct-leak-telemetry", path, lineno))
            continue
        violations.append(
            Violation(
                rule="ct-leak-telemetry",
                path=path,
                line=lineno,
                col=col,
                message=detail,
                source=line_text(path, lineno),
            )
        )

    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    report.violations = violations
    per_rule: Dict[str, int] = {rid: 0 for rid, _ in RULES}
    for v in violations:
        per_rule[v.rule] = per_rule.get(v.rule, 0) + 1
    report.stats = {
        "privkey_classes": len(cat.privkey_class_names),
        "secret_attrs": sum(
            len(a) for a in cat.class_secret_attrs.values()
        ),
        "seeded_functions": len(cat.seed_params),
        "region": sum(
            1 for st in engine.states.values() if st.analyzed
        ),
        "suppressed": n_supp,
        **{f"findings[{rid}]": n for rid, n in per_rule.items()},
    }
    return report


def ct_violations(pkg: Optional[Package] = None) -> List[Violation]:
    return analyze(pkg).violations


def new_ct_violations(
    pkg: Optional[Package] = None,
    baseline_path: Optional[str] = None,
) -> List[Violation]:
    violations = ct_violations(pkg)
    baseline = load_baseline(baseline_path or CT_BASELINE_PATH)
    return new_violations(violations, baseline)


def update_ct_baseline(
    pkg: Optional[Package] = None,
    baseline_path: Optional[str] = None,
) -> Dict[str, int]:
    return save_baseline(
        ct_violations(pkg),
        baseline_path or CT_BASELINE_PATH,
        note=CT_BASELINE_NOTE,
    )
