"""Secret-taint dataflow over the PR-5 call graph.

A two-level taint lattice — CLEAN < CARRIER < SECRET — born at the
machine-derived sources in sources.py and propagated through
arithmetic, hashing, containers, and internal calls (monotone fixpoint
with per-function parameter joins and return summaries, the tmsafe
worklist architecture).

**SECRET** is raw key material: private scalars, seed bytes, signing
nonces, expanded-key intermediates. Timing sinks (branch/index/
compare/pow) and telemetry sinks fire on it.

**CARRIER** is an object *holding* secrets — a PrivKey instance, a
FilePVKey record. Method calls on a carrier declassify by name (sign /
pub_key / address / verify_* publish their output by design); reading
a raw-material attribute off one re-enters SECRET; everything else
reads CLEAN. Only the lifetime sinks fire on carriers — parking a key
object in a module-global cache keeps the secret alive exactly like
parking its bytes — while its `.height`-style public fields flow
freely through the consensus plane without dragging taint along.

Declassification boundaries (the only taint kills):

- a call to `sign` / `pub_key` / `address` / `public_*` / `verify_*` /
  `type` / `equals`: the output is published by design — a signature,
  a public key, an address. Their *internals* are still analyzed.
- `libs/ctutil.bytes_eq`: the comparison's boolean is public by
  contract (its path to the answer is the constant-structure part);
- a store into a public-named attribute (`self._pub = ...`): the
  pubkey-derivation boundary;
- structural reads: `len()`, `type()`, `isinstance()`, `is None`
  identity tests — they observe shape/presence, not bytes.

Two sink classes (the rule split in __init__.RULES):

**timing** — ct-secret-branch (if/while/ternary/assert tests, range()
loop bounds, comprehension conditions on a SECRET), ct-secret-index
(subscript whose index involves a SECRET), ct-secret-compare
(==/!=/in/not-in with a SECRET operand — route through
libs/ctutil.bytes_eq), ct-vartime-pow (two-arg pow / ** with a SECRET
exponent: CPython's non-modular pow is value-dependent bignum work;
three-arg pow is the sanctioned modular inverse).

**lifetime/exfiltration** — ct-leak-telemetry (f-strings, repr/print/
format, exception args, logging-method calls, any call into
libs/{log,metrics,profiler,trace} with a SECRET argument) and
ct-leak-lifetime (a SECRET-or-CARRIER argument into crypto/sigcache,
or stored into a module-global name or container — the PR-9 shared
sigcache/memo/ring surfaces, where a value outlives its operation).

Iterating secret *bytes* (`for b in key`) is deliberately not a
branch finding: the iteration count is the public length, not the
value. Only a secret-valued bound (`range(k)`, `while k:`) is.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..tmcheck.callgraph import CallSite, FuncInfo, Package
from .sources import PUBLIC_ATTR_RE, SecretCatalog

__all__ = ["SecretEngine", "Finding", "CLEAN", "CARRIER", "SECRET"]

FuncKey = Tuple[str, str]

CLEAN = 0
CARRIER = 1
SECRET = 2

# method names whose call RESULT is public by design, wherever the
# receiver's secrecy came from (the operation's published output)
_DECLASS_METHODS = {
    "sign",
    "pub_key",
    "address",
    "type",
    "equals",
    "sign_vote",
    "sign_proposal",
    # wire-encoding a group element is a publication boundary: the
    # bytes it produces (a compressed point — the signature's R, a
    # public key) are published by design
    "compress",
}
_DECLASS_PREFIXES = ("pub", "public", "verify")

# resolved publication boundaries: group-element serializers whose
# output ships in a signature or key — no taint flows in (branching
# on a to-be-published value is benign) and none comes out
_PUBLICATION_TARGETS = {
    ("crypto/ristretto.py", "encode"),
    ("crypto/ed25519_math.py", "compress"),
    ("crypto/secp256k1.py", "_compress"),
}

# builtins observing structure, not content
_STRUCTURAL_BUILTINS = {
    "len",
    "type",
    "isinstance",
    "issubclass",
    "hasattr",
    "callable",
    "id",
}

# method names on a CARRIER that hand back the raw material
_CARRIER_RAW_METHODS = {"bytes", "to_bytes", "secret_bytes"}

# logging-method names: `X.debug(secret)` is exfiltration no matter
# what X resolves to
_LOG_METHODS = {
    "debug",
    "info",
    "warning",
    "warn",
    "error",
    "exception",
    "critical",
}

# telemetry plane modules: any resolved call into them with a secret
# argument is a leak (metrics labels, trace span attrs, profiler tags)
_TELEMETRY_SUFFIXES = (
    "libs/log.py",
    "libs/metrics.py",
    "libs/profiler.py",
    "libs/trace.py",
)

# shared-container plane (PR-9 catalog): values stored here outlive
# the operation that produced them
_LIFETIME_SUFFIXES = ("crypto/sigcache.py",)

_CONTAINER_MUTATORS = {
    "append",
    "appendleft",
    "add",
    "extend",
    "insert",
    "update",
    "setdefault",
    "put",
    "put_nowait",
}


class Finding:
    __slots__ = ("rule", "path", "lineno", "col", "detail", "key")

    def __init__(self, rule, path, lineno, col, detail, key):
        self.rule = rule
        self.path = path
        self.lineno = lineno
        self.col = col
        self.detail = detail
        self.key = key


class _FnState:
    """Polymorphic return summary: `ret_base` is the return taint with
    clean parameters (internal births only — urandom, secret self
    attrs, secret-returning callees); `param_dep` says whether tainted
    arguments can raise it. A call site's result is then
    max(ret_base, args-if-param_dep) — shared arithmetic (point_add,
    field helpers) called with public inputs stays clean even though
    the signing plane also routes secrets through it."""

    __slots__ = ("param_taint", "ret_base", "param_dep", "analyzed")

    def __init__(self) -> None:
        self.param_taint: Dict[str, int] = {}
        self.ret_base: int = CLEAN
        self.param_dep = False
        self.analyzed = False

    def call_ret(self, max_arg: int) -> int:
        return max(self.ret_base, max_arg if self.param_dep else CLEAN)


class SecretEngine:
    def __init__(self, pkg: Package, cat: SecretCatalog) -> None:
        self.pkg = pkg
        self.cat = cat
        self.states: Dict[FuncKey, _FnState] = {}
        self.callers: Dict[FuncKey, Set[FuncKey]] = {}
        self.parent: Dict[FuncKey, Tuple[FuncKey, int]] = {}
        self.findings: Dict[Tuple[str, str, int, int], Finding] = {}
        self._work: List[FuncKey] = []
        self._queued: Set[FuncKey] = set()
        # (path, class) -> set of method FuncKeys, for re-analysis when
        # a secret attr is discovered on the class mid-run
        self._class_methods: Dict[Tuple[str, str], Set[FuncKey]] = {}
        for key, fi in pkg.functions.items():
            if fi.class_name:
                self._class_methods.setdefault(
                    (fi.path, fi.class_name), set()
                ).add(key)
        # module path -> names assigned at module top level (the
        # process-global lifetime surface)
        self._module_globals: Dict[str, Set[str]] = {}
        for path, mod in pkg.modules.items():
            g: Set[str] = set()
            for node in mod.tree.body:
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign):
                    targets = [node.target]
                for t in targets:
                    if isinstance(t, ast.Name):
                        g.add(t.id)
            self._module_globals[path] = g

    # -- public --

    def run(self) -> List[Finding]:
        # every function is analyzed at least once: sources can be born
        # mid-body (os.urandom, a generate() call, a secret attr load),
        # not just at seeded parameters
        for key in self.pkg.functions:
            st = self._state(key)
            for p in self.cat.seed_params.get(key, ()):
                st.param_taint[p] = SECRET
            for p in self.cat.carrier_params.get(key, ()):
                if st.param_taint.get(p, CLEAN) < CARRIER:
                    st.param_taint[p] = CARRIER
            self._enqueue(key)
        while self._work:
            key = self._work.pop()
            self._queued.discard(key)
            self._analyze(key)
        return sorted(
            self.findings.values(),
            key=lambda f: (f.path, f.lineno, f.col, f.rule),
        )

    def chain(self, key: FuncKey) -> List[str]:
        seen: Set[FuncKey] = set()
        out: List[str] = []
        cur: Optional[FuncKey] = key
        while cur is not None and cur not in seen:
            seen.add(cur)
            fi = self.pkg.functions.get(cur)
            out.append(fi.render() if fi else f"{cur[0]}:{cur[1]}")
            nxt = self.parent.get(cur)
            cur = nxt[0] if nxt else None
        out.reverse()
        return out

    # -- machinery --

    def _state(self, key: FuncKey) -> _FnState:
        st = self.states.get(key)
        if st is None:
            st = _FnState()
            self.states[key] = st
        return st

    def _enqueue(self, key: FuncKey) -> None:
        if key not in self._queued:
            self._queued.add(key)
            self._work.append(key)

    def _flow_into(
        self, caller: FuncKey, callee: FuncKey, taints: Dict[str, int],
        lineno: int,
    ) -> None:
        st = self._state(callee)
        grew = False
        for name, kind in taints.items():
            if kind > st.param_taint.get(name, CLEAN):
                st.param_taint[name] = kind
                grew = True
        if grew or not st.analyzed:
            self.parent.setdefault(callee, (caller, lineno))
            self._enqueue(callee)
        self.callers.setdefault(callee, set()).add(caller)

    def _ret_update(
        self, key: FuncKey, ret_base: int, param_dep: bool
    ) -> None:
        st = self._state(key)
        if ret_base > st.ret_base or (param_dep and not st.param_dep):
            st.ret_base = max(st.ret_base, ret_base)
            st.param_dep = st.param_dep or param_dep
            for c in self.callers.get(key, ()):
                self._enqueue(c)

    def mark_secret_attr(self, path: str, cls: str, attr: str) -> None:
        """A method stored raw SECRET material into self.<attr>: the
        class now carries it; re-analyze its methods so reads see it.
        PubKey-plane classes are exempt — everything stored in one is
        published output (the derivation boundary already fired)."""
        if self.cat.is_pubkey_class(cls):
            return
        key = (path, cls)
        attrs = self.cat.class_secret_attrs.setdefault(key, set())
        if attr not in attrs:
            attrs.add(attr)
            for mk in self._class_methods.get(key, ()):
                self._enqueue(mk)

    def report(self, rule, key, node, detail) -> None:
        fi = self.pkg.functions[key]
        k = (rule, fi.path, node.lineno, node.col_offset)
        if k not in self.findings:
            self.findings[k] = Finding(
                rule, fi.path, node.lineno, node.col_offset, detail, key
            )

    def has_finding_at(self, key: FuncKey, lineno: int) -> bool:
        fi = self.pkg.functions[key]
        return any(
            k[1] == fi.path and k[2] == lineno for k in self.findings
        )

    def _analyze(self, key: FuncKey) -> None:
        fi = self.pkg.functions.get(key)
        if fi is None:
            return
        st = self._state(key)
        st.analyzed = True
        # concrete pass: actual joined parameter taints, reporting on
        concrete = _BodyWalker(self, fi, dict(st.param_taint), True)
        concrete.run()
        # base pass: clean params, internal births only
        if st.param_taint:
            base = _BodyWalker(self, fi, {}, False)
            base.run()
            ret_base = base.ret
        else:
            ret_base = concrete.ret
        # generic pass: hypothetical all-secret params — does the
        # return depend on what callers pass in?
        args = fi.node.args
        params = [
            a.arg
            for a in args.posonlyargs + args.args + args.kwonlyargs
            if a.arg not in ("self", "cls")
        ]
        param_dep = False
        if params:
            generic = _BodyWalker(
                self, fi, {p: SECRET for p in params}, False
            )
            generic.run()
            param_dep = generic.ret > ret_base
        self._ret_update(key, ret_base, param_dep)


class _BodyWalker:
    """One function body, statements in program order, operands always
    evaluated (the tmsafe never-short-circuit discipline)."""

    def __init__(
        self,
        eng: SecretEngine,
        fi: FuncInfo,
        env: Dict[str, int],
        report_mode: bool,
    ) -> None:
        self.eng = eng
        self.fi = fi
        self.key = fi.key
        self.env: Dict[str, int] = env
        self.report_mode = report_mode
        self.ret: int = CLEAN
        self.globals = eng._module_globals.get(fi.path, set())
        self.global_decls: Set[str] = set()
        self.class_key = (fi.path, fi.class_name) if fi.class_name else None
        self.in_crypto_plane = (
            "/crypto/" in fi.path or "/privval/" in fi.path
            or fi.path.startswith(("crypto/", "privval/"))
        )
        self.sites: Dict[Tuple[int, int], CallSite] = {
            (s.lineno, s.col): s for s in fi.calls
        }

    def run(self) -> None:
        for node in self.fi.node.body:
            self.stmt(node)

    # -- helpers --

    def _report(self, rule, key, node, detail) -> None:
        # the base and generic passes run hypothetical environments —
        # only the concrete pass reports
        if self.report_mode:
            self.eng.report(rule, key, node, detail)

    def _secret_attrs(self) -> Set[str]:
        if self.class_key is None:
            return set()
        attrs = self.eng.cat.class_secret_attrs.get(self.class_key, set())
        if self.eng.cat.is_privkey_class(self.class_key[1]):
            # inherited raw material: a subclass method reads the attrs
            # its base assigned (class_secret_attrs is keyed by the
            # assigning class, so the closure-wide union covers MRO)
            return attrs | self.eng.cat.raw_attr_union()
        return attrs

    def _assign_name(self, name: str, kind: int) -> None:
        if kind:
            self.env[name] = kind
        else:
            self.env.pop(name, None)

    def _assign_target(self, tgt: ast.AST, kind: int, value=None) -> None:
        if isinstance(tgt, ast.Name):
            if kind and tgt.id in self.global_decls:
                self._report(
                    "ct-leak-lifetime",
                    self.key,
                    tgt,
                    f"secret assigned to module-global `{tgt.id}` — key "
                    "material outliving its operation in process state",
                )
            self._assign_name(tgt.id, kind)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            parts = None
            if isinstance(value, (ast.Tuple, ast.List)) and len(
                value.elts
            ) == len(tgt.elts):
                parts = value.elts
            for i, elt in enumerate(tgt.elts):
                if parts is not None:
                    self._assign_target(elt, self.expr(parts[i]))
                else:
                    self._assign_target(elt, kind)
        elif isinstance(tgt, ast.Attribute):
            if (
                isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
                and self.fi.class_name
            ):
                if (
                    self.report_mode
                    and kind == SECRET
                    and not PUBLIC_ATTR_RE.search(tgt.attr)
                ):
                    self.eng.mark_secret_attr(
                        self.fi.path, self.fi.class_name, tgt.attr
                    )
                # a CARRIER store or a public-named store is not raw
                # material entering the class: carrier attrs read back
                # through the annotation-derived secret_attr_names set,
                # and a public-named attr is the pubkey-derivation
                # declassification boundary
            else:
                self.expr(tgt.value)
        elif isinstance(tgt, ast.Subscript):
            self._store_subscript(tgt, kind)
        elif isinstance(tgt, ast.Starred):
            self._assign_target(tgt.value, kind)

    def _store_subscript(self, tgt: ast.Subscript, kind: int) -> None:
        idx_kind = self.expr(tgt.slice)
        if idx_kind == SECRET:
            self._report(
                "ct-secret-index",
                self.key,
                tgt,
                "subscript STORE indexed by a secret-derived value — "
                "the access pattern is data-dependent",
            )
        base = tgt.value
        self.expr(base)
        if (
            kind
            and isinstance(base, ast.Name)
            and base.id in self.globals
            and base.id not in self.env
        ):
            self._report(
                "ct-leak-lifetime",
                self.key,
                tgt,
                f"secret stored into module-global container "
                f"`{base.id}` — the PR-9 shared-cache lifetime rule: "
                "key material must not outlive its operation",
            )
        if kind and isinstance(base, ast.Name):
            cur = self.env.get(base.id, CLEAN)
            if kind > cur:
                self.env[base.id] = kind

    # -- statements --

    def stmt(self, node: ast.AST) -> None:
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            return
        if isinstance(node, ast.Global):
            self.global_decls.update(node.names)
            return
        if isinstance(node, ast.Assign):
            kind = self.expr(node.value)
            for tgt in node.targets:
                self._assign_target(tgt, kind, node.value)
        elif isinstance(node, ast.AnnAssign):
            kind = self.expr(node.value) if node.value else CLEAN
            self._assign_target(node.target, kind, node.value)
        elif isinstance(node, ast.AugAssign):
            kind = self.expr(node.value)
            if isinstance(node.target, ast.Name):
                cur = self.env.get(node.target.id, CLEAN)
                self._assign_name(node.target.id, max(cur, kind))
            else:
                self._assign_target(node.target, kind)
        elif isinstance(node, ast.Expr):
            self.expr(node.value)
        elif isinstance(node, ast.Return):
            if node.value is not None:
                self.ret = max(self.ret, self.expr(node.value))
        elif isinstance(node, ast.If):
            self._branch(node.test, node.body, node.orelse, "if")
        elif isinstance(node, ast.While):
            t = self.expr(node.test)
            self._maybe_branch_report(node.test, t, "while")
            self._loop_body(node.body)
            for s in node.orelse:
                self.stmt(s)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            self._for(node)
        elif isinstance(node, ast.Try):
            for s in node.body:
                self.stmt(s)
            for h in node.handlers:
                for s in h.body:
                    self.stmt(s)
            for s in node.orelse:
                self.stmt(s)
            for s in node.finalbody:
                self.stmt(s)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                kind = self.expr(item.context_expr)
                if item.optional_vars is not None:
                    self._assign_target(item.optional_vars, kind)
            for s in node.body:
                self.stmt(s)
        elif isinstance(node, ast.Assert):
            t = self.expr(node.test)
            self._maybe_branch_report(node.test, t, "assert")
            if node.msg is not None:
                m = self.expr(node.msg)
                if m == SECRET:
                    self._report(
                        "ct-leak-telemetry",
                        self.key,
                        node,
                        "secret in an assert message — AssertionError "
                        "text reaches logs and crash reports",
                    )
        elif isinstance(node, ast.Raise):
            self._raise(node)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.env.pop(t.id, None)
                else:
                    self.expr(t)
        elif isinstance(
            node,
            (ast.Nonlocal, ast.Pass, ast.Break, ast.Continue, ast.Import,
             ast.ImportFrom),
        ):
            return
        else:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self.expr(child)
                elif isinstance(child, ast.stmt):
                    self.stmt(child)

    def _raise(self, node: ast.Raise) -> None:
        if node.exc is None:
            return
        exc = node.exc
        if isinstance(exc, ast.Call):
            kinds = [self.expr(a) for a in exc.args]
            kinds += [self.expr(kw.value) for kw in exc.keywords]
            if any(k == SECRET for k in kinds):
                self._report(
                    "ct-leak-telemetry",
                    self.key,
                    node,
                    "secret in exception args — error text propagates "
                    "to logs, RPC error frames, and crash reports",
                )
        else:
            self.expr(exc)

    def _maybe_branch_report(self, test, kind: int, what: str) -> None:
        if kind != SECRET or not self.report_mode:
            return
        # an Eq/In compare in the test already produced the (more
        # specific) ct-secret-compare on this line
        if self.eng.has_finding_at(self.key, test.lineno):
            return
        self.eng.report(
            "ct-secret-branch",
            self.key,
            test,
            f"secret-dependent `{what}` — control flow is a function "
            "of key material (structure-not-cycles: the trace shape "
            "must not depend on secret bits)",
        )

    def _branch(self, test, body, orelse, what: str) -> None:
        t = self.expr(test)
        self._maybe_branch_report(test, t, what)
        snap = dict(self.env)
        for s in body:
            self.stmt(s)
        env_b = self.env
        self.env = dict(snap)
        for s in orelse:
            self.stmt(s)
        for name, kind in env_b.items():
            if kind > self.env.get(name, CLEAN):
                self.env[name] = kind

    def _loop_body(self, body) -> None:
        for _ in range(2):
            for s in body:
                self.stmt(s)

    def _for(self, node) -> None:
        iter_kind = self.expr(node.iter)
        # `for _ in range(secret)` — the COUNT is the secret. Direct
        # iteration over secret bytes has a public count (the length)
        # and binds secret elements instead.
        if (
            isinstance(node.iter, ast.Call)
            and isinstance(node.iter.func, ast.Name)
            and node.iter.func.id == "range"
            and any(self.expr(a) == SECRET for a in node.iter.args)
        ):
            self._report(
                "ct-secret-branch",
                self.key,
                node.iter,
                "loop bound derived from a secret — iteration count "
                "is a function of key material",
            )
        self._assign_target(node.target, iter_kind)
        self._loop_body(node.body)
        for s in node.orelse:
            self.stmt(s)

    # -- expressions --

    def expr(self, node: Optional[ast.AST]) -> int:
        if node is None:
            return CLEAN
        if isinstance(node, ast.Name):
            return self.env.get(node.id, CLEAN)
        if isinstance(node, ast.Constant):
            return CLEAN
        if isinstance(node, ast.Attribute):
            return self._attribute(node)
        if isinstance(node, (ast.Await, ast.Starred)):
            return self.expr(node.value)
        if isinstance(node, ast.BinOp):
            left = self.expr(node.left)
            right = self.expr(node.right)
            if isinstance(node.op, ast.Pow) and right == SECRET:
                self._report(
                    "ct-vartime-pow",
                    self.key,
                    node,
                    "`**` with a secret exponent — non-modular "
                    "exponentiation is value-dependent bignum work; "
                    "use 3-arg pow",
                )
            return max(left, right)
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, ast.BoolOp):
            return max(self.expr(v) for v in node.values)
        if isinstance(node, ast.Compare):
            return self._compare(node)
        if isinstance(node, ast.IfExp):
            t = self.expr(node.test)
            self._maybe_branch_report(node.test, t, "ternary")
            return max(self.expr(node.body), self.expr(node.orelse))
        if isinstance(node, ast.Subscript):
            return self._subscript(node)
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
            kinds = [self.expr(e) for e in node.elts]
            return max(kinds) if kinds else CLEAN
        if isinstance(node, ast.Dict):
            kinds = [self.expr(k) for k in node.keys if k is not None]
            kinds += [self.expr(v) for v in node.values]
            return max(kinds) if kinds else CLEAN
        if isinstance(
            node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
        ):
            return self._comprehension(node)
        if isinstance(node, ast.JoinedStr):
            leak = CLEAN
            for v in node.values:
                leak = max(leak, self.expr(v))
            if leak == SECRET:
                self._report(
                    "ct-leak-telemetry",
                    self.key,
                    node,
                    "secret interpolated into an f-string — formatted "
                    "text flows to logs/errors/operator surfaces",
                )
                return SECRET
            # rendering a CARRIER goes through its (redacting)
            # __repr__ — the dataclass-repr rule polices that shape
            return CLEAN
        if isinstance(node, ast.FormattedValue):
            return self.expr(node.value)
        if isinstance(node, ast.Lambda):
            return CLEAN
        if isinstance(node, ast.Slice):
            return max(
                self.expr(node.lower),
                self.expr(node.upper),
                self.expr(node.step),
            )
        if isinstance(node, ast.NamedExpr):
            kind = self.expr(node.value)
            self._assign_target(node.target, kind)
            return kind
        kinds = [
            self.expr(c)
            for c in ast.iter_child_nodes(node)
            if isinstance(c, ast.expr)
        ]
        return max(kinds) if kinds else CLEAN

    def _attribute(self, node: ast.Attribute) -> int:
        if node.attr in self.eng.cat.secret_attr_names:
            # an annotation-declared key field (FilePVKey.priv_key):
            # the read yields the key *object*
            self.expr(node.value)
            return CARRIER
        if (
            isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr in self._secret_attrs()
        ):
            return SECRET
        base = self.expr(node.value)
        if base == CLEAN:
            return CLEAN
        if PUBLIC_ATTR_RE.search(node.attr):
            # reading a public-named field off a secret carrier:
            # priv.pub — the derivation boundary again
            return CLEAN
        if base == CARRIER:
            # a key object's non-public fields: raw-material names
            # re-enter SECRET; anything else (heights, timestamps,
            # flags riding on the same record) reads CLEAN
            if node.attr in self.eng.cat.raw_attr_union():
                return SECRET
            return CLEAN
        return base

    def _compare(self, node: ast.Compare) -> int:
        kinds = [self.expr(node.left)]
        kinds.extend(self.expr(c) for c in node.comparators)
        top = max(kinds)
        if top != SECRET:
            # carrier comparisons are object-level decisions; the
            # byte-compare inside an equals() body is analyzed there
            return CLEAN
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            # identity against None observes presence, not bytes
            return CLEAN
        if any(
            isinstance(op, (ast.Eq, ast.NotEq, ast.In, ast.NotIn))
            for op in node.ops
        ):
            self._report(
                "ct-secret-compare",
                self.key,
                node,
                "equality/membership on a secret — `==` short-circuits "
                "at the first differing byte; route through "
                "libs/ctutil.bytes_eq",
            )
        return SECRET

    def _subscript(self, node: ast.Subscript) -> int:
        base = self.expr(node.value)
        idx_kind = self.expr(node.slice)
        if idx_kind == SECRET and isinstance(node.ctx, ast.Load):
            self._report(
                "ct-secret-index",
                self.key,
                node,
                "table lookup indexed by a secret-derived value — the "
                "memory-access pattern leaks through cache timing; "
                "use an arithmetic-mask scan",
            )
        return max(base, idx_kind)

    def _comprehension(self, node) -> int:
        for gen in node.generators:
            iter_kind = self.expr(gen.iter)
            if (
                isinstance(gen.iter, ast.Call)
                and isinstance(gen.iter.func, ast.Name)
                and gen.iter.func.id == "range"
                and any(self.expr(a) == SECRET for a in gen.iter.args)
            ):
                self._report(
                    "ct-secret-branch",
                    self.key,
                    gen.iter,
                    "comprehension bound derived from a secret",
                )
            self._assign_target(gen.target, iter_kind)
            for cond in gen.ifs:
                t = self.expr(cond)
                self._maybe_branch_report(cond, t, "comprehension-if")
        if isinstance(node, ast.DictComp):
            return max(self.expr(node.key), self.expr(node.value))
        return self.expr(node.elt)

    # -- calls --

    def _call(self, node: ast.Call) -> int:
        func = node.func
        recv_kind = CLEAN
        attr = ""
        if isinstance(func, ast.Attribute):
            recv_kind = self.expr(func.value)
            attr = func.attr
        arg_kinds = [self.expr(a) for a in node.args]
        kw_kinds: Dict[str, int] = {}
        spread = CLEAN
        for kw in node.keywords:
            k = self.expr(kw.value)
            if kw.arg is not None:
                kw_kinds[kw.arg] = k
            else:
                spread = max(spread, k)
        max_arg = max([CLEAN, spread] + arg_kinds + list(kw_kinds.values()))

        name = func.id if isinstance(func, ast.Name) else ""

        # container mutation taints the receiver
        if (
            attr in _CONTAINER_MUTATORS
            and max_arg
            and isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
        ):
            recv_name = func.value.id
            if recv_name in self.globals and recv_name not in self.env:
                self._report(
                    "ct-leak-lifetime",
                    self.key,
                    node,
                    f"secret pushed into module-global container "
                    f"`{recv_name}` — key material outliving its "
                    "operation in process state",
                )
            cur = self.env.get(recv_name, CLEAN)
            if max_arg > cur:
                self.env[recv_name] = max_arg

        # declassification + structural builtins
        if name in _STRUCTURAL_BUILTINS:
            return CLEAN
        if name in ("bytes_eq", "compare_digest") or attr in (
            "bytes_eq",
            "compare_digest",
        ):
            # the constant-structure comparators: their boolean is
            # public by contract
            return CLEAN
        if name == "pow":
            if len(node.args) == 2 and arg_kinds[1] == SECRET:
                self._report(
                    "ct-vartime-pow",
                    self.key,
                    node,
                    "two-arg pow() with a secret exponent — "
                    "value-dependent bignum work; the modular 3-arg "
                    "form is the sanctioned inverse/exponent path",
                )
            return max_arg
        if name in ("repr", "ascii"):
            if max_arg == SECRET:
                self._report(
                    "ct-leak-telemetry",
                    self.key,
                    node,
                    "repr() of a secret — renders key bytes into text",
                )
            return CLEAN
        if name == "print":
            if max_arg == SECRET:
                self._report(
                    "ct-leak-telemetry",
                    self.key,
                    node,
                    "secret printed to an operator surface",
                )
            return CLEAN
        if name == "format" or attr == "format":
            if max_arg == SECRET or recv_kind == SECRET:
                self._report(
                    "ct-leak-telemetry",
                    self.key,
                    node,
                    "secret passed through str.format — formatted text "
                    "flows to logs/errors/operator surfaces",
                )
            return CLEAN

        # logging methods: exfiltration regardless of receiver identity
        if attr in _LOG_METHODS and max_arg == SECRET:
            self._report(
                "ct-leak-telemetry",
                self.key,
                node,
                f"secret argument to `.{attr}()` — a logging call; "
                "key material must never reach the log plane",
            )

        # entropy birth: urandom in the crypto/privval planes mints
        # key material and signing nonces
        if attr == "urandom" or name == "urandom":
            return SECRET if self.in_crypto_plane else CLEAN

        site = self.sites.get((node.lineno, node.col_offset))
        if site is not None and site.target is not None:
            return self._internal_call(node, site, arg_kinds, kw_kinds,
                                       recv_kind, max_arg)

        # unresolved method call on a tainted receiver: declassified by
        # name; a raw-extraction name on a carrier re-enters SECRET;
        # `.hex()`/`.to_bytes()` on raw material keep secrecy
        if attr:
            if attr in _DECLASS_METHODS or attr.startswith(
                _DECLASS_PREFIXES
            ):
                return CLEAN
            if recv_kind == CARRIER:
                base = (
                    SECRET if attr in _CARRIER_RAW_METHODS else CARRIER
                )
                return max(base, max_arg)
            return max(recv_kind, max_arg)
        return max_arg

    def _internal_call(
        self, node, site, arg_kinds, kw_kinds, recv_kind, max_arg
    ) -> int:
        target: FuncKey = site.target
        callee = self.eng.pkg.functions.get(target)
        method = target[1].split(".")[-1]

        # sinks on the resolved target's home module
        if max(max_arg, recv_kind) and target[0].endswith(
            _LIFETIME_SUFFIXES
        ):
            self._report(
                "ct-leak-lifetime",
                self.key,
                node,
                f"secret argument into {target[1]} "
                "(crypto/sigcache.py) — cache keys must be derived "
                "from public data only (pubkey, sign_bytes, "
                "signature)",
            )
        elif max_arg == SECRET and target[0].endswith(
            _TELEMETRY_SUFFIXES
        ):
            self._report(
                "ct-leak-telemetry",
                self.key,
                node,
                f"secret argument into {target[1]} — the telemetry "
                "plane (log/metrics/trace/profiler) is an operator "
                "surface",
            )

        if callee is None:
            return max(recv_kind, max_arg)

        declass = (
            method in _DECLASS_METHODS
            or method.startswith(_DECLASS_PREFIXES)
            or target in _PUBLICATION_TARGETS
        )

        if self.report_mode and not declass and target != self.key:
            # taint only flows through non-published interfaces, and
            # only from the concrete pass (the hypothetical passes
            # must not poison callee summaries)
            taints: Dict[str, int] = {}
            args = callee.node.args
            positional = [a.arg for a in args.posonlyargs + args.args]
            params = positional + [a.arg for a in args.kwonlyargs]
            pos = list(positional)
            if pos and pos[0] in ("self", "cls"):
                pos = pos[1:]
            for i, kind in enumerate(arg_kinds):
                if kind and i < len(pos):
                    taints[pos[i]] = max(taints.get(pos[i], CLEAN), kind)
            for kname, kind in kw_kinds.items():
                if kind and kname in params:
                    taints[kname] = max(taints.get(kname, CLEAN), kind)
            self.eng._flow_into(self.key, target, taints, node.lineno)

        cls_name = target[1].split(".")[0] if "." in target[1] else ""
        if method == "__init__":
            if self.eng.cat.is_privkey_class(cls_name):
                # constructing a key object yields a carrier even from
                # clean args (the instance is key material either way)
                return CARRIER
            if self.eng.cat.is_pubkey_class(cls_name):
                # a PubKey object is published output — the derivation
                # boundary already declassified what went into it
                return CLEAN
            return max(recv_kind, max_arg)
        if target in self.eng.cat.secret_return_keys:
            return CARRIER
        if declass:
            return CLEAN
        return self.eng._state(target).call_ret(max_arg)
