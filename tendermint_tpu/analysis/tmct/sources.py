"""Secret-source derivation for tmct — machine-derived, never hand-listed.

What counts as a secret is read off the package itself, the same
golden-source discipline as tmsafe's entry families:

- **PrivKey subclasses** (transitive closure over base-class names
  rooted at `crypto.keys.PrivKey`): every instance attribute the class
  assigns whose name does not read as public (`_pub*`, `pub*`,
  `addr*`, `*path`, `*type*`, `*name*`) is key material, and every
  non-self parameter of `__init__` is the raw key bytes entering it.
- **Secret-typed annotations**: any attribute or parameter annotated
  with a PrivKey type anywhere in the package (FilePVKey.priv_key) is
  a secret *carrier* — method calls on it are declassified by name
  (`sign`, `pub_key`, `address`, ...), everything else stays secret.
- **Secret-returning functions**: a return annotation naming a
  PrivKey type (factories like keys.generate_priv_key) marks the
  call's result secret at every call site.
- **Entropy births**: `os.urandom` inside crypto/ and privval/
  modules mints key material and signing nonces (sr25519's merlin
  witness, secp256k1 keygen). Outside those planes urandom feeds
  request IDs and jitter — not in scope.

Signing nonces and expanded-key intermediates (RFC 6979 state, the
sr25519 witness scalar, `_expand_seed`'s clamped `a`) need no special
listing: they are *derived* from the seeds above and the engine's
propagation reaches them interprocedurally.

The one AST-invisible sink lives here too: a `@dataclass` whose
secret-typed field lacks `repr=False` gets a generated __repr__ that
embeds the secret — reported as ct-leak-telemetry at the field's line.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from ..tmcheck.callgraph import Package

__all__ = ["SecretCatalog", "derive_catalog", "PUBLIC_ATTR_RE"]

FuncKey = Tuple[str, str]
ClassKey = Tuple[str, str]  # (path, class name)

# attribute names that hold public material even on a PrivKey subclass
PUBLIC_ATTR_RE = re.compile(
    r"^_{0,2}(pub|addr)|path$|type|name$", re.IGNORECASE
)

# the hierarchy root every key class derives from
_ROOT_CLASS = "PrivKey"


class SecretCatalog:
    """Everything the engine treats as a secret seed, plus the findings
    only a class-shape scan (not dataflow) can produce."""

    def __init__(self) -> None:
        # PrivKey + all transitive subclasses, by leaf name
        self.privkey_class_names: Set[str] = set()
        # PubKey + subclasses: the *public* plane — everything stored
        # in one is published output (derivation declassifies), so
        # dynamic secret-attr growth never applies to them
        self.pubkey_class_names: Set[str] = set()
        # (path, class) -> secret instance-attribute names
        self.class_secret_attrs: Dict[ClassKey, Set[str]] = {}
        # attribute names annotated with a PrivKey type anywhere
        self.secret_attr_names: Set[str] = set()
        # function keys whose return annotation names a PrivKey type
        self.secret_return_keys: Set[FuncKey] = set()
        # raw-material params (PrivKey-subclass __init__ args: the key
        # bytes themselves): key -> param names, seeded SECRET
        self.seed_params: Dict[FuncKey, Set[str]] = {}
        # PrivKey-typed params package-wide (key *objects*): seeded
        # CARRIER — method calls on them declassify by name, their raw
        # fields re-enter SECRET
        self.carrier_params: Dict[FuncKey, Set[str]] = {}
        # dataclass fields leaking through a generated __repr__:
        # (path, lineno, col, detail)
        self.repr_leaks: List[Tuple[str, int, int, str]] = []

    def is_privkey_class(self, name: str) -> bool:
        return name.split(".")[-1] in self.privkey_class_names

    def is_pubkey_class(self, name: str) -> bool:
        return name.split(".")[-1] in self.pubkey_class_names

    def raw_attr_union(self) -> Set[str]:
        """Every raw-material attribute name across key classes —
        reading one of these off a CARRIER re-enters SECRET."""
        out: Set[str] = set()
        for attrs in self.class_secret_attrs.values():
            out |= attrs
        return out


def _leaf(name: str) -> str:
    return name.split(".")[-1]


def _ann_names(ann) -> Set[str]:
    """Leaf identifiers in an annotation, including string annotations
    ('PrivKeySecp256k1') and Optional/quoted forms."""
    out: Set[str] = set()
    if ann is None:
        return out
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        for tok in re.findall(r"[A-Za-z_][A-Za-z0-9_]*", ann.value):
            out.add(tok)
        return out
    for node in ast.walk(ann):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            for tok in re.findall(r"[A-Za-z_][A-Za-z0-9_]*", node.value):
                out.add(tok)
    return out


def _is_dataclass(node: ast.ClassDef) -> bool:
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = ""
        if isinstance(target, ast.Name):
            name = target.id
        elif isinstance(target, ast.Attribute):
            name = target.attr
        if name == "dataclass":
            return True
    return False


def _field_suppresses_repr(value) -> bool:
    """True iff the field default is `field(..., repr=False)`."""
    if not isinstance(value, ast.Call):
        return False
    fn = value.func
    name = fn.id if isinstance(fn, ast.Name) else getattr(fn, "attr", "")
    if name != "field":
        return False
    for kw in value.keywords:
        if (
            kw.arg == "repr"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is False
        ):
            return True
    return False


def derive_catalog(pkg: Package) -> SecretCatalog:
    cat = SecretCatalog()

    # -- transitive PrivKey subclass closure over base-name edges --
    class_bases: Dict[str, Set[str]] = {}
    for mod in pkg.modules.values():
        for cname, rec in mod.classes.items():
            class_bases.setdefault(cname, set()).update(
                _leaf(b) for b in rec["bases"]
            )
    def closure(root: str) -> Set[str]:
        out = {root}
        grew = True
        while grew:
            grew = False
            for cname, bases in class_bases.items():
                if cname not in out and bases & out:
                    out.add(cname)
                    grew = True
        return out

    names = closure(_ROOT_CLASS)
    cat.privkey_class_names = names
    cat.pubkey_class_names = closure("PubKey")

    for path, mod in pkg.modules.items():
        for cname, rec in mod.classes.items():
            node: ast.ClassDef = rec["node"]

            # -- annotation-derived carriers (any class) --
            for item in node.body:
                if isinstance(item, ast.AnnAssign) and isinstance(
                    item.target, ast.Name
                ):
                    if _ann_names(item.annotation) & names:
                        cat.secret_attr_names.add(item.target.id)
                        if _is_dataclass(node) and not (
                            _field_suppresses_repr(item.value)
                        ):
                            cat.repr_leaks.append(
                                (
                                    path,
                                    item.lineno,
                                    item.col_offset,
                                    f"dataclass {cname}.{item.target.id} "
                                    "is a secret-typed field without "
                                    "field(repr=False): the generated "
                                    "__repr__ embeds key material in "
                                    "any log/debug/assert rendering",
                                )
                            )

            if cname not in names:
                continue

            # -- PrivKey subclass: secret attrs + ctor params --
            key: ClassKey = (path, cname)
            attrs: Set[str] = set()
            for item in ast.walk(node):
                if isinstance(item, ast.Assign):
                    for tgt in item.targets:
                        if (
                            isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                            and not PUBLIC_ATTR_RE.search(tgt.attr)
                        ):
                            attrs.add(tgt.attr)
            for slot_src in node.body:
                if (
                    isinstance(slot_src, ast.Assign)
                    and any(
                        isinstance(t, ast.Name) and t.id == "__slots__"
                        for t in slot_src.targets
                    )
                    and isinstance(slot_src.value, (ast.Tuple, ast.List))
                ):
                    for elt in slot_src.value.elts:
                        if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str
                        ):
                            if not PUBLIC_ATTR_RE.search(elt.value):
                                attrs.add(elt.value)
            if attrs:
                cat.class_secret_attrs[key] = attrs

            init_key = (path, f"{cname}.__init__")
            fi = pkg.functions.get(init_key)
            if fi is not None:
                args = fi.node.args
                params = {
                    a.arg
                    for a in args.posonlyargs + args.args + args.kwonlyargs
                    if a.arg not in ("self", "cls")
                }
                if params:
                    cat.seed_params[init_key] = params

    # -- secret-typed params and returns, package-wide --
    for fkey, fi in pkg.functions.items():
        args = fi.node.args
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            if a.annotation is not None and (
                _ann_names(a.annotation) & names
            ):
                cat.carrier_params.setdefault(fkey, set()).add(a.arg)
        ret_ann = getattr(fi.node, "returns", None)
        if ret_ann is not None and _ann_names(ret_ann) & names:
            cat.secret_return_keys.add(fkey)

    return cat
