"""tmlint — AST-based static analyzer for consensus invariants.

BFT safety rests on every replica computing byte-identical sign-bytes
and block hashes (SURVEY.md "Determinism & safety"): a replica whose
hash input depends on wall-clock time, an unseeded RNG, float
rounding, or set iteration order will sign a different byte stream
than its peers and the network forks or halts. Those are
consensus-failure bugs, not style issues — so they are enforced
mechanically here, the way the reference leans on `go vet` and
`go test -race`.

Architecture:

- A `Rule` inspects one parsed `Module` (AST + source lines +
  precomputed parent links) and yields `Violation`s. Rules declare
  their own path scope — determinism rules only fire in
  consensus-critical modules, device rules only on the JAX hot path,
  lock rules in any module that imports `threading`.
- Per-line suppressions: `# tmlint: disable=<rule>[,<rule>...]` on
  the offending line, or alone on the line directly above it. A
  suppression is a reviewed, justified exception — the comment should
  say why (docs/static_analysis.md has the policy).
- A checked-in baseline (analysis/baseline.json) records accepted
  pre-existing violations by content fingerprint (rule + path + the
  offending source line's hash), so unrelated edits never shift it
  and NEW violations fail while grandfathered ones pass.
  `python scripts/lint.py --baseline-update` regenerates it.

The analyzer is pure stdlib (`ast`, `json`, `hashlib`) and must stay
fast: tests/test_lint.py budgets the full-package run at 10 s on CPU.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

__all__ = [
    "Violation",
    "Module",
    "Rule",
    "comment_cover_lines",
    "all_rules",
    "rule_ids",
    "check_source",
    "check_file",
    "check_package",
    "load_baseline",
    "save_baseline",
    "baseline_counts",
    "new_violations",
    "package_root",
    "BASELINE_PATH",
]

# ---------------------------------------------------------------------------
# scopes

# Modules whose output feeds sign-bytes / block hashes / proto
# encodings directly: any nondeterminism here IS a consensus fork.
CONSENSUS_CRITICAL_PREFIXES = ("types/", "encoding/")
CONSENSUS_CRITICAL_FILES = {
    "crypto/merkle.py",
    "crypto/tmhash.py",
    "consensus/state.py",
}

# Message-driven state machines replayed by the schedulefuzz suites:
# an unseeded global RNG here breaks seed-exact replay of a failure.
REPLAY_PREFIXES = ("consensus/", "blocksync/", "statesync/")

# The JAX device hot path: implicit host syncs and recompile-forcing
# shape leaks hide here.
DEVICE_FILES = {"crypto/batch.py", "crypto/tpu_verifier.py"}
DEVICE_PREFIXES = ("parallel/",)


def is_consensus_critical(path: str) -> bool:
    return path in CONSENSUS_CRITICAL_FILES or path.startswith(
        CONSENSUS_CRITICAL_PREFIXES
    )


def is_replay_scope(path: str) -> bool:
    return is_consensus_critical(path) or path.startswith(REPLAY_PREFIXES)


def is_device_scope(path: str) -> bool:
    return path in DEVICE_FILES or path.startswith(DEVICE_PREFIXES)


# ---------------------------------------------------------------------------
# data model


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    path: str  # posix path relative to the package root
    line: int
    col: int
    message: str
    source: str = ""  # stripped offending source line (fingerprint input)

    def fingerprint(self) -> str:
        """Content-addressed identity: stable across unrelated edits
        (line numbers don't participate), distinct per offending line
        text. Identical lines in one file share a fingerprint and are
        baseline-counted, so duplicating a grandfathered bad line is
        still caught as new."""
        h = hashlib.sha1(
            self.source.strip().encode("utf-8", "replace")
        ).hexdigest()[:12]
        return f"{self.rule}:{self.path}:{h}"

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"[{self.rule}] {self.message}"
        )


_SUPPRESS_RE = re.compile(r"#\s*tmlint:\s*disable=([A-Za-z0-9_\-, ]+)")


def comment_cover_lines(lines, i: int, text: str):
    """Line numbers an annotation at 1-based line `i` covers: itself,
    plus — when it sits inside a comment block — the first code line
    below the block. This is the comment-block-above suppression
    convention shared by EVERY analyzer in the family
    (tmlint/tmcheck/tmrace/tmtrace/tmlive); one implementation so they
    can never drift on what a suppression comment reaches."""
    out = [i]
    if text.lstrip().startswith("#"):
        j = i + 1
        while j <= len(lines) and (
            not lines[j - 1].strip()
            or lines[j - 1].lstrip().startswith("#")
        ):
            j += 1
        if j <= len(lines):
            out.append(j)
    return out


class Module:
    """One parsed source file plus the per-module indexes every rule
    needs: source lines, suppression map, parent links, and the
    imported-module set (lock rules scope on `import threading`)."""

    def __init__(
        self, path: str, source: str, tree: Optional[ast.AST] = None
    ) -> None:
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        # `tree` reuses an already-parsed AST (the full-gate substrate
        # shared with tmcheck's call graph); rules only read it
        self.tree = tree if tree is not None else ast.parse(
            source, filename=path
        )
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.imports: Set[str] = set()
        self.from_imports: Dict[str, str] = {}  # local name -> module
        # local name -> (module, original name): lets rules match
        # `from time import time as now` as time.time
        self.from_import_orig: Dict[str, tuple] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports.add(a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                self.imports.add(node.module.split(".")[0])
                for a in node.names:
                    local = a.asname or a.name
                    self.from_imports[local] = node.module
                    self.from_import_orig[local] = (node.module, a.name)
        self.suppressed: Dict[int, Set[str]] = {}
        for i, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            # a suppression inside a comment block also covers the
            # first code line below it — justification comments are
            # encouraged to span several lines
            for ln in comment_cover_lines(self.lines, i, text):
                self.suppressed.setdefault(ln, set()).update(rules)

    @property
    def imports_threading(self) -> bool:
        return "threading" in self.imports

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def is_suppressed(self, rule_id: str, lineno: int) -> bool:
        rules = self.suppressed.get(lineno)
        return bool(rules) and (rule_id in rules or "all" in rules)

    def enclosing(self, node: ast.AST, *types) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, types):
                return cur
            cur = self.parents.get(cur)
        return None

    def enclosing_function(self, node: ast.AST):
        return self.enclosing(
            node, ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda
        )


class Rule:
    """One invariant check. Subclasses set `id`, `title`, `rationale`
    (surfaced by --list-rules and the docs catalog) and implement
    `applies()` + `check()`."""

    id = ""
    title = ""
    rationale = ""

    def applies(self, mod: Module) -> bool:
        raise NotImplementedError

    def check(self, mod: Module) -> Iterator[Violation]:
        raise NotImplementedError

    def violation(self, mod: Module, node: ast.AST, message: str) -> Violation:
        lineno = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Violation(
            rule=self.id,
            path=mod.path,
            line=lineno,
            col=col,
            message=message,
            source=mod.line_text(lineno).strip(),
        )


def dotted_name(node: ast.AST) -> str:
    """`a.b.c` for Name/Attribute chains, '' for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


# ---------------------------------------------------------------------------
# registry + runner

_RULES: List[Rule] = []


def register(rule_cls):
    _RULES.append(rule_cls())
    return rule_cls


def all_rules() -> List[Rule]:
    if not _RULES:  # pragma: no cover - import cycle guard
        raise RuntimeError("rule modules not imported")
    return list(_RULES)


def rule_ids() -> List[str]:
    return [r.id for r in all_rules()]


def select_rules(only: Optional[Sequence[str]]) -> List[Rule]:
    rules = all_rules()
    if not only:
        return rules
    wanted = set(only)
    unknown = wanted - {r.id for r in rules}
    if unknown:
        raise ValueError(f"unknown rule id(s): {sorted(unknown)}")
    return [r for r in rules if r.id in wanted]


def check_source(
    source: str,
    path: str,
    rules: Optional[Sequence[str]] = None,
    tree: Optional[ast.AST] = None,
) -> List[Violation]:
    """Analyze one source string as if it lived at `path` (posix,
    relative to the package root — the path drives rule scoping, which
    is how the fixture tests exercise scoped rules on synthetic
    files)."""
    mod = Module(path, source, tree=tree)
    out: List[Violation] = []
    for rule in select_rules(rules):
        if not rule.applies(mod):
            continue
        for v in rule.check(mod):
            if not mod.is_suppressed(v.rule, v.line):
                out.append(v)
    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return out


def check_file(
    abspath: str,
    relpath: str,
    rules: Optional[Sequence[str]] = None,
) -> List[Violation]:
    with open(abspath, "r", encoding="utf-8") as f:
        source = f.read()
    return check_source(source, relpath, rules)


def package_root() -> str:
    """The tendermint_tpu package directory (the default analysis
    root)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def iter_py_files(root: str) -> Iterator[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d != "__pycache__" and not d.startswith(".")
        )
        for name in sorted(filenames):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def check_package(
    root: Optional[str] = None,
    rules: Optional[Sequence[str]] = None,
    pkg=None,
) -> List[Violation]:
    """`pkg`: an already-built tmcheck callgraph Package — the shared
    full-gate substrate. Files it skipped (unparseable) still go
    through the file path so parse-error reporting is unchanged."""
    root = root or (pkg.root if pkg is not None else package_root())
    out: List[Violation] = []
    for abspath in iter_py_files(root):
        rel = os.path.relpath(abspath, root).replace(os.sep, "/")
        try:
            shared = pkg.modules.get(rel) if pkg is not None else None
            if shared is not None:
                # full-gate substrate: reuse the call-graph build's
                # source AND parsed tree (one parse per module per gate)
                out.extend(
                    check_source(
                        shared.source, rel, rules, tree=shared.tree
                    )
                )
            else:
                out.extend(check_file(abspath, rel, rules))
        except SyntaxError as e:  # pragma: no cover - broken tree
            out.append(
                Violation(
                    rule="parse-error",
                    path=rel,
                    line=e.lineno or 1,
                    col=e.offset or 0,
                    message=f"could not parse: {e.msg}",
                    source="",
                )
            )
    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return out


# ---------------------------------------------------------------------------
# baseline

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")


def baseline_counts(violations: Iterable[Violation]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for v in violations:
        fp = v.fingerprint()
        counts[fp] = counts.get(fp, 0) + 1
    return counts


def load_baseline(path: Optional[str] = None) -> Dict[str, int]:
    path = path or BASELINE_PATH
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    entries = data.get("entries", {})
    return {str(k): int(v) for k, v in entries.items()}


def save_baseline(
    violations: Iterable[Violation],
    path: Optional[str] = None,
    note: Optional[str] = None,
) -> Dict[str, int]:
    path = path or BASELINE_PATH
    counts = baseline_counts(violations)
    data = {
        "version": 1,
        "generated_by": "scripts/lint.py --baseline-update",
        # the note names the suppression syntax for THIS tool's
        # findings — tmrace passes its own (race-ok / guarded-by)
        "note": note
        or (
            "Accepted pre-existing violations, fingerprinted by "
            "rule:path:sha1(source_line)[:12]. New violations are "
            "anything over these counts. Do not hand-edit counts to "
            "sneak a new violation in — fix it or suppress it with a "
            "justified '# tmlint: disable=<rule>' comment."
        ),
        "entries": {k: counts[k] for k in sorted(counts)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=False)
        f.write("\n")
    return counts


def new_violations(
    violations: Sequence[Violation], baseline: Dict[str, int]
) -> List[Violation]:
    """Violations exceeding their fingerprint's baseline allowance.
    When a fingerprint's current count is over budget, every
    occurrence is reported (content-identical lines are
    indistinguishable; the report notes the allowance)."""
    counts = baseline_counts(violations)
    out: List[Violation] = []
    for v in violations:
        fp = v.fingerprint()
        allowed = baseline.get(fp, 0)
        if counts[fp] > allowed:
            if allowed:
                v = dataclasses.replace(
                    v,
                    message=(
                        f"{v.message} [{counts[fp]} occurrences, "
                        f"baseline allows {allowed}]"
                    ),
                )
            out.append(v)
    return out


# rule modules self-register on import; importing them here keeps
# `import tmlint` sufficient for every caller (CLI, tests, conftest)
from . import rules_determinism  # noqa: E402,F401
from . import rules_device  # noqa: E402,F401
from . import rules_locks  # noqa: E402,F401
