"""Block — Header + Data(Txs) + Evidence + LastCommit.

Reference: types/block.go (Block :42-310, fillHeader :98, Hash :112,
MakePartSet :129, MaxDataBytes :264-305, MakeBlock :310), proto field
numbers proto/tendermint/types/block.pb.go:27-30.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..encoding.proto import FieldReader, ProtoWriter, iter_fields
from .block_id import BlockID
from .commit import Commit, max_commit_bytes
from .evidence import (
    Evidence,
    evidence_from_proto,
    evidence_list_hash,
    evidence_to_proto,
)
from .header import Consensus, Header
from .part_set import BLOCK_PART_SIZE_BYTES, PartSet
from .tx import txs_hash

__all__ = [
    "Block",
    "make_block",
    "MAX_HEADER_BYTES",
    "MAX_OVERHEAD_FOR_BLOCK",
    "max_data_bytes",
    "max_data_bytes_no_evidence",
]

MAX_HEADER_BYTES = 626  # reference: types/block.go:28
MAX_OVERHEAD_FOR_BLOCK = 11  # reference: types/block.go:38


def max_data_bytes(
    max_bytes: int, evidence_bytes: int, vals_count: int
) -> int:
    """reference: types/block.go:264-283."""
    md = (
        max_bytes
        - MAX_OVERHEAD_FOR_BLOCK
        - MAX_HEADER_BYTES
        - max_commit_bytes(vals_count)
        - evidence_bytes
    )
    if md < 0:
        raise ValueError(
            f"negative MaxDataBytes: Block.MaxBytes={max_bytes} too small"
        )
    return md


def max_data_bytes_no_evidence(max_bytes: int, vals_count: int) -> int:
    """reference: types/block.go:289-305."""
    md = (
        max_bytes
        - MAX_OVERHEAD_FOR_BLOCK
        - MAX_HEADER_BYTES
        - max_commit_bytes(vals_count)
    )
    if md < 0:
        raise ValueError(
            f"negative MaxDataBytesNoEvidence: Block.MaxBytes={max_bytes}"
        )
    return md


@dataclass
class Block:
    header: Header = field(default_factory=Header)
    txs: List[bytes] = field(default_factory=list)
    evidence: List[Evidence] = field(default_factory=list)
    last_commit: Optional[Commit] = None

    def fill_header(self) -> None:
        """Populate derived header hashes (reference: types/block.go:98)."""
        h = self.header
        if not h.last_commit_hash and self.last_commit is not None:
            h.last_commit_hash = self.last_commit.hash()
        if not h.data_hash:
            h.data_hash = txs_hash(self.txs)
        if not h.evidence_hash:
            h.evidence_hash = evidence_list_hash(self.evidence)

    def hash(self) -> bytes:
        """Header hash; empty if the block is incomplete
        (reference: types/block.go:112-124)."""
        if self.last_commit is None:
            return b""
        self.fill_header()
        return self.header.hash()

    def hashes_to(self, h: bytes) -> bool:
        return bool(h) and self.hash() == h

    def make_part_set(
        self, part_size: int = BLOCK_PART_SIZE_BYTES
    ) -> PartSet:
        return PartSet.from_data(self.to_proto(), part_size)

    def block_id(self, part_size: int = BLOCK_PART_SIZE_BYTES) -> BlockID:
        return BlockID(
            hash=self.hash(),
            part_set_header=self.make_part_set(part_size).header(),
        )

    def size(self) -> int:
        return len(self.to_proto())

    def validate_basic(self) -> None:
        """reference: types/block.go:52-96. Validates the header as
        received — no backfilling, so absent hashes fail the equality
        checks instead of being silently computed."""
        h = self.header
        h.validate_basic()
        if self.last_commit is None:
            if h.height != 1:
                raise ValueError("nil LastCommit")
        else:
            self.last_commit.validate_basic()
            if h.last_commit_hash != self.last_commit.hash():
                raise ValueError("wrong Header.LastCommitHash")
        if h.data_hash != txs_hash(self.txs):
            raise ValueError("wrong Header.DataHash")
        if h.evidence_hash != evidence_list_hash(self.evidence):
            raise ValueError("wrong Header.EvidenceHash")

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.message(1, self.header.to_proto())  # nullable=false
        data = ProtoWriter()
        for tx in self.txs:
            data.bytes(1, tx)
        w.message(2, data.finish())  # nullable=false
        ev = ProtoWriter()
        for e in self.evidence:
            ev.message(1, evidence_to_proto(e))
        w.message(3, ev.finish())  # nullable=false
        if self.last_commit is not None:
            w.message(4, self.last_commit.to_proto())
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "Block":
        r = FieldReader(data)
        header = Header.from_proto(r.get(1, b""))
        txs: List[bytes] = []
        d = r.get(2)
        if d:
            txs = [v for f, _wt, v in iter_fields(d) if f == 1]
        evidence: List[Evidence] = []
        e = r.get(3)
        if e:
            evidence = [
                evidence_from_proto(v)
                for f, _wt, v in iter_fields(e)
                if f == 1
            ]
        lc = r.get(4)
        return cls(
            header=header,
            txs=txs,
            evidence=evidence,
            last_commit=Commit.from_proto(lc) if lc is not None else None,
        )


def make_block(
    height: int,
    txs: List[bytes],
    last_commit: Optional[Commit],
    evidence: List[Evidence],
) -> Block:
    """reference: types/block.go:310-325."""
    block = Block(
        header=Header(version=Consensus(), height=height),
        txs=list(txs),
        evidence=list(evidence),
        last_commit=last_commit,
    )
    block.fill_header()
    return block
