"""Transactions and their merkle root.

Reference: types/tx.go (Tx.Hash :24 = sha256, Txs.Hash :34 = merkle root
of tx hashes, Txs.Proof), types/tx.go:60-90.
"""

from __future__ import annotations

from typing import List, Sequence

from ..crypto import merkle, tmhash

__all__ = ["tx_hash", "txs_hash", "tx_key", "txs_proofs"]


def tx_hash(tx: bytes) -> bytes:
    return tmhash.sum256(tx)


def tx_key(tx: bytes) -> bytes:
    """Index key for mempool/indexer maps (reference: types/tx.go TxKey)."""
    return tx_hash(tx)


def txs_hash(txs: Sequence[bytes]) -> bytes:
    """Merkle root over per-tx hashes (leaves are TxIDs)."""
    return merkle.hash_from_byte_slices([tx_hash(tx) for tx in txs])


def txs_proofs(txs: Sequence[bytes]) -> List[merkle.Proof]:
    """Merkle proof for each tx against txs_hash."""
    _, proofs = merkle.proofs_from_byte_slices(
        [tx_hash(tx) for tx in txs]
    )
    return proofs
