"""VoteSet — collects and tallies signed votes for one (height, round, type).

Reference: types/vote_set.go. Tracks the canonical per-validator vote
list plus per-block tallies so conflicting (double-sign) votes are
detected and bounded; first block to cross 2/3 becomes `maj23`.

Single-threaded by design: the consensus core serializes all vote
ingestion (reference's mutex guards multi-goroutine access; our runtime
feeds the set from one task — see consensus.state).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..libs.bits import BitArray
from .block_id import BlockID
from .canonical import PRECOMMIT_TYPE
from .commit import Commit, CommitSig
from .validator import ValidatorSet
from .vote import Vote

__all__ = ["VoteSet", "ConflictingVoteError", "MAX_VOTES_COUNT"]

MAX_VOTES_COUNT = 10000  # DoS bound (reference: types/vote_set.go:18)


class ConflictingVoteError(Exception):
    """A validator signed two different blocks at the same H/R/S
    (reference: types/errors.go NewConflictingVoteError)."""

    def __init__(self, vote_a: Vote, vote_b: Vote) -> None:
        super().__init__(
            f"conflicting votes from validator "
            f"{vote_a.validator_address.hex()}"
        )
        self.vote_a = vote_a
        self.vote_b = vote_b


def _vote_commit_sig(vote: Optional[Vote]) -> CommitSig:
    """reference: types/vote.go Vote.CommitSig."""
    if vote is None:
        return CommitSig.absent()
    if vote.is_nil():
        return CommitSig.for_nil(
            vote.signature, vote.validator_address, vote.timestamp_ns
        )
    return CommitSig.for_block(
        vote.signature, vote.validator_address, vote.timestamp_ns
    )


@dataclass
class _BlockVotes:
    """Votes for one particular block key
    (reference: types/vote_set.go:647-677)."""

    peer_maj23: bool
    bit_array: BitArray
    votes: List[Optional[Vote]]
    sum: int = 0

    @classmethod
    def new(cls, peer_maj23: bool, num_validators: int) -> "_BlockVotes":
        return cls(
            peer_maj23=peer_maj23,
            bit_array=BitArray(num_validators),
            votes=[None] * num_validators,
        )

    def add_verified_vote(self, vote: Vote, voting_power: int) -> None:
        i = vote.validator_index
        if self.votes[i] is None:
            self.bit_array.set(i, True)
            # tmlive: bounded=fixed-size slot list: new() allocates
            # [None] * num_validators and this only fills slot i in
            # range — never appends
            self.votes[i] = vote
            self.sum += voting_power

    def get_by_index(self, index: int) -> Optional[Vote]:
        return self.votes[index]


class VoteSet:
    def __init__(
        self,
        chain_id: str,
        height: int,
        round_: int,
        signed_msg_type: int,
        val_set: ValidatorSet,
    ) -> None:
        if height == 0:
            raise ValueError("cannot make VoteSet for height 0")
        self.chain_id = chain_id
        self.height = height
        self.round = round_
        self.signed_msg_type = signed_msg_type
        self.val_set = val_set
        n = val_set.size()
        self.votes_bit_array = BitArray(n)
        self.votes: List[Optional[Vote]] = [None] * n
        self.sum = 0
        self.maj23: Optional[BlockID] = None
        self.votes_by_block: Dict[bytes, _BlockVotes] = {}
        self.peer_maj23s: Dict[str, BlockID] = {}

    def size(self) -> int:
        return self.val_set.size()

    # -- vote ingestion (reference: types/vote_set.go:143-300) --

    def add_vote(self, vote: Vote) -> bool:
        """True if the vote was valid and new; False for duplicates.
        Raises ValueError for invalid votes, ConflictingVoteError for
        double-signs (which may still have been added if the block is
        being tracked)."""
        if vote is None:
            raise ValueError("nil vote")
        val_index = vote.validator_index
        val_addr = vote.validator_address
        block_key = vote.block_id.key()

        if val_index < 0:
            raise ValueError("index < 0")
        if not val_addr:
            raise ValueError("empty address")
        if (
            vote.height != self.height
            or vote.round != self.round
            or vote.type != self.signed_msg_type
        ):
            raise ValueError(
                f"expected {self.height}/{self.round}/"
                f"{self.signed_msg_type}, got {vote.height}/"
                f"{vote.round}/{vote.type}"
            )
        lookup_addr, val = self.val_set.get_by_index(val_index)
        if val is None:
            raise ValueError(
                f"cannot find validator {val_index} in valSet of size "
                f"{self.val_set.size()}"
            )
        if val_addr != lookup_addr:
            raise ValueError(
                "vote.ValidatorAddress does not match address for index"
            )
        existing = self._get_vote(val_index, block_key)
        if existing is not None:
            if existing.signature == vote.signature:
                return False  # duplicate
            raise ValueError("non-deterministic signature")
        # Check signature (raises on failure). The verify-ahead queue
        # (consensus/state.py _preverify_votes) may have already batch-
        # verified this exact (pubkey, sign-bytes, signature) triple on
        # device against THIS height's validator set — Vote.verify then
        # hits the verified-signature cache (crypto.sigcache) instead of
        # re-running the curve math. The cache key binds the triple's
        # exact bytes, so it can never widen acceptance; the address/
        # index/HRS checks above run unconditionally either way.
        vote.verify(self.chain_id, val.pub_key)
        added, conflicting = self._add_verified_vote(
            vote, block_key, val.voting_power
        )
        if conflicting is not None:
            raise ConflictingVoteError(conflicting, vote)
        if not added:
            raise RuntimeError("expected to add non-conflicting vote")
        return added

    def _get_vote(
        self, val_index: int, block_key: bytes
    ) -> Optional[Vote]:
        existing = self.votes[val_index]
        if existing is not None and existing.block_id.key() == block_key:
            return existing
        bv = self.votes_by_block.get(block_key)
        if bv is not None:
            return bv.get_by_index(val_index)
        return None

    def _add_verified_vote(
        self, vote: Vote, block_key: bytes, voting_power: int
    ) -> Tuple[bool, Optional[Vote]]:
        val_index = vote.validator_index
        conflicting: Optional[Vote] = None

        existing = self.votes[val_index]
        if existing is not None:
            # conflicting vote from same validator
            conflicting = existing
            if self.maj23 is not None and self.maj23.key() == block_key:
                self.votes[val_index] = vote
                self.votes_bit_array.set(val_index, True)
        else:
            self.votes[val_index] = vote
            self.votes_bit_array.set(val_index, True)
            self.sum += voting_power

        bv = self.votes_by_block.get(block_key)
        if bv is not None:
            if conflicting is not None and not bv.peer_maj23:
                return False, conflicting
        else:
            if conflicting is not None:
                return False, conflicting
            bv = _BlockVotes.new(False, self.val_set.size())
            self.votes_by_block[block_key] = bv

        orig_sum = bv.sum
        quorum = self.val_set.total_voting_power() * 2 // 3 + 1
        bv.add_verified_vote(vote, voting_power)

        if orig_sum < quorum <= bv.sum and self.maj23 is None:
            self.maj23 = vote.block_id
            for i, v in enumerate(bv.votes):
                if v is not None:
                    self.votes[i] = v
        return True, conflicting

    def set_peer_maj23(self, peer_id: str, block_id: BlockID) -> None:
        """A peer claims 2/3 for block_id; start tracking it
        (reference: types/vote_set.go:309-342)."""
        block_key = block_id.key()
        existing = self.peer_maj23s.get(peer_id)
        if existing is not None:
            if existing == block_id:
                return
            raise ValueError(
                f"conflicting blockID from peer {peer_id}"
            )
        self.peer_maj23s[peer_id] = block_id
        bv = self.votes_by_block.get(block_key)
        if bv is not None:
            bv.peer_maj23 = True
        else:
            self.votes_by_block[block_key] = _BlockVotes.new(
                True, self.val_set.size()
            )

    # -- queries --

    def bit_array(self) -> BitArray:
        return self.votes_bit_array.copy()

    def bit_array_by_block_id(
        self, block_id: BlockID
    ) -> Optional[BitArray]:
        bv = self.votes_by_block.get(block_id.key())
        return bv.bit_array.copy() if bv is not None else None

    def get_by_index(self, val_index: int) -> Optional[Vote]:
        if val_index < 0 or val_index >= len(self.votes):
            return None
        return self.votes[val_index]

    def get_by_address(self, address: bytes) -> Optional[Vote]:
        idx, val = self.val_set.get_by_address(address)
        if val is None:
            return None
        return self.votes[idx]

    def list_votes(self) -> List[Vote]:
        return [v for v in self.votes if v is not None]

    def has_two_thirds_majority(self) -> bool:
        return self.maj23 is not None

    def is_commit(self) -> bool:
        return (
            self.signed_msg_type == PRECOMMIT_TYPE
            and self.maj23 is not None
        )

    def has_two_thirds_any(self) -> bool:
        return self.sum > self.val_set.total_voting_power() * 2 // 3

    def has_all(self) -> bool:
        return self.sum == self.val_set.total_voting_power()

    def two_thirds_majority(self) -> Tuple[BlockID, bool]:
        if self.maj23 is not None:
            return self.maj23, True
        return BlockID(), False

    # -- commit construction (reference: types/vote_set.go:613-637) --

    def make_commit(self) -> Commit:
        if self.signed_msg_type != PRECOMMIT_TYPE:
            raise ValueError(
                "cannot MakeCommit unless VoteSet type is precommit"
            )
        if self.maj23 is None:
            raise ValueError(
                "cannot MakeCommit unless a blockhash has +2/3"
            )
        commit_sigs: List[CommitSig] = []
        for v in self.votes:
            cs = _vote_commit_sig(v)
            if cs.is_for_block() and v.block_id != self.maj23:
                cs = CommitSig.absent()
            commit_sigs.append(cs)
        return Commit(
            height=self.height,
            round=self.round,
            block_id=self.maj23,
            signatures=commit_sigs,
        )


def commit_to_vote_set(
    chain_id: str, commit: Commit, vals: ValidatorSet
) -> VoteSet:
    """Reconstruct a precommit VoteSet from a Commit
    (reference: types/block.go:776-788)."""
    vote_set = VoteSet(
        chain_id, commit.height, commit.round, PRECOMMIT_TYPE, vals
    )
    for idx, cs in enumerate(commit.signatures):
        if cs.is_absent():
            continue
        added = vote_set.add_vote(commit.get_vote(idx))
        if not added:
            raise RuntimeError("failed to reconstruct LastCommit")
    return vote_set
