"""Core consensus datatypes (reference: types/ package)."""

from .block import (  # noqa: F401
    Block,
    make_block,
    max_data_bytes,
    max_data_bytes_no_evidence,
)
from .block_id import BlockID, PartSetHeader  # noqa: F401
from .canonical import (  # noqa: F401
    PRECOMMIT_TYPE,
    PREVOTE_TYPE,
    PROPOSAL_TYPE,
    proposal_sign_bytes,
    vote_sign_bytes,
)
from .commit import (  # noqa: F401
    BLOCK_ID_FLAG_ABSENT,
    BLOCK_ID_FLAG_COMMIT,
    BLOCK_ID_FLAG_NIL,
    Commit,
    CommitSig,
)
from .evidence import (  # noqa: F401
    DuplicateVoteEvidence,
    Evidence,
    LightClientAttackEvidence,
    evidence_from_proto,
    evidence_list_hash,
    evidence_to_proto,
)
from .genesis import GenesisDoc, GenesisValidator  # noqa: F401
from .header import Consensus, Header  # noqa: F401
from .light import LightBlock, SignedHeader  # noqa: F401
from .params import (  # noqa: F401
    BlockParams,
    ConsensusParams,
    EvidenceParams,
    ValidatorParams,
    VersionParams,
)
from .part_set import BLOCK_PART_SIZE_BYTES, Part, PartSet  # noqa: F401
from .proposal import Proposal  # noqa: F401
from .timestamp import now_ns  # noqa: F401
from .tx import tx_hash, tx_key, txs_hash  # noqa: F401
from .validation import (  # noqa: F401
    Fraction,
    InvalidCommitError,
    NotEnoughVotingPowerError,
    verify_commit,
    verify_commit_light,
    verify_commit_light_trusting,
)
from .validator import Validator, ValidatorSet  # noqa: F401
from .vote import Vote  # noqa: F401
from .vote_set import (  # noqa: F401
    ConflictingVoteError,
    VoteSet,
    commit_to_vote_set,
)
