"""Commit verification — the framework's north-star hot path.

Mirrors types/validation.go exactly: VerifyCommit (:25, checks ALL sigs
for incentivization), VerifyCommitLight (:59, stops at 2/3),
VerifyCommitLightTrusting (:94, fraction of a *trusted* set, lookup by
address), and the batch/single pair (:152/:265). The batch path packs a
whole Commit's (pubkey, sign-bytes, signature) triples into one
crypto.batch verifier — on TPU that is a single device program over the
padded batch (tendermint_tpu.ops.ed25519_kernel), sharded across the
mesh for large validator sets (tendermint_tpu.parallel.sharding).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from ..crypto.batch import create_batch_verifier, supports_batch_verifier
from ..libs import trace
from .block_id import BlockID
from .commit import Commit, CommitSig
from .validator import ValidatorSet

__all__ = [
    "BATCH_VERIFY_THRESHOLD",
    "Fraction",
    "NotEnoughVotingPowerError",
    "InvalidCommitError",
    "collect_commit_light",
    "verify_commit",
    "verify_commit_light",
    "verify_commit_light_trusting",
    "verify_triples_grouped",
]

BATCH_VERIFY_THRESHOLD = 2  # reference: types/validation.go:12


@dataclass(frozen=True)
class Fraction:
    """Trust level, e.g. 1/3 (reference: libs/math/fraction.go)."""

    numerator: int
    denominator: int

    def validate(self) -> None:
        if self.denominator == 0:
            raise ValueError("fraction has zero denominator")


class InvalidCommitError(ValueError):
    pass


class NotEnoughVotingPowerError(InvalidCommitError):
    def __init__(self, got: int, needed: int) -> None:
        super().__init__(
            f"invalid commit -- insufficient voting power: got {got}, "
            f"needed more than {needed}"
        )
        self.got = got
        self.needed = needed


def _should_batch_verify(vals: ValidatorSet, commit: Commit) -> bool:
    return len(
        commit.signatures
    ) >= BATCH_VERIFY_THRESHOLD and supports_batch_verifier(
        vals.get_proposer().pub_key
    )


def verify_commit(
    chain_id: str,
    vals: ValidatorSet,
    block_id: BlockID,
    height: int,
    commit: Commit,
) -> None:
    """+2/3 signed, verifying ALL signatures (incentivization needs the
    full bitmap — reference: types/validation.go:18-51)."""
    _verify_basic(vals, commit, height, block_id)
    voting_power_needed = vals.total_voting_power() * 2 // 3
    ignore = lambda c: c.is_absent()  # noqa: E731
    count = lambda c: c.is_for_block()  # noqa: E731
    if _should_batch_verify(vals, commit):
        _verify_commit_batch(
            chain_id, vals, commit, voting_power_needed,
            ignore, count, True, True,
        )
    else:
        _verify_commit_single(
            chain_id, vals, commit, voting_power_needed,
            ignore, count, True, True,
        )


def verify_commit_light(
    chain_id: str,
    vals: ValidatorSet,
    block_id: BlockID,
    height: int,
    commit: Commit,
) -> None:
    """+2/3 signed, early exit once the tally crosses 2/3
    (reference: types/validation.go:55-85)."""
    _verify_basic(vals, commit, height, block_id)
    voting_power_needed = vals.total_voting_power() * 2 // 3
    ignore = lambda c: not c.is_for_block()  # noqa: E731
    count = lambda c: True  # noqa: E731
    if _should_batch_verify(vals, commit):
        _verify_commit_batch(
            chain_id, vals, commit, voting_power_needed,
            ignore, count, False, True,
        )
    else:
        _verify_commit_single(
            chain_id, vals, commit, voting_power_needed,
            ignore, count, False, True,
        )


def verify_commit_light_trusting(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    trust_level: Fraction,
) -> None:
    """trust_level (e.g. 1/3) of a TRUSTED validator set signed; lookup
    by address since sets needn't match
    (reference: types/validation.go:87-131)."""
    if vals is None:
        raise InvalidCommitError("nil validator set")
    trust_level.validate()
    if commit is None:
        raise InvalidCommitError("nil commit")
    total_mul = vals.total_voting_power() * trust_level.numerator
    if total_mul >= 1 << 63:
        raise InvalidCommitError(
            "int64 overflow while calculating voting power needed"
        )
    voting_power_needed = total_mul // trust_level.denominator
    ignore = lambda c: not c.is_for_block()  # noqa: E731
    count = lambda c: True  # noqa: E731
    if _should_batch_verify(vals, commit):
        _verify_commit_batch(
            chain_id, vals, commit, voting_power_needed,
            ignore, count, False, False,
        )
    else:
        _verify_commit_single(
            chain_id, vals, commit, voting_power_needed,
            ignore, count, False, False,
        )


def collect_commit_light(
    chain_id: str,
    vals: ValidatorSet,
    block_id: BlockID,
    height: int,
    commit: Commit,
) -> list:
    """verify_commit_light's host-side half: run every non-signature
    check (set size, height, block ID, 2/3 tally with the same
    early-exit) and return the (pub_key, sign_bytes, signature)
    triples verify_commit_light would have signature-checked — without
    checking them. Callers fold triples from MANY commits into one
    device batch (the light client's sequential group sync,
    light/client.py); any triple failing there must be re-verified
    per-commit for the reference's exact error. Mirrors the tally
    semantics of types/validation.go:55-85."""
    _verify_basic(vals, commit, height, block_id)
    voting_power_needed = vals.total_voting_power() * 2 // 3
    tallied = 0
    out = []
    # lazy per-index encode (template-cached): this early-exit variant
    # skips nil votes and stops at 2/3, so a full precompute would pay
    # for rows it discards — same policy as _verify_commit_batch
    for idx, commit_sig in enumerate(commit.signatures):
        if not commit_sig.is_for_block():
            continue
        # look_up_by_index semantics (same-set verification)
        val = vals.validators[idx]
        out.append(
            (
                val.pub_key,
                commit.vote_sign_bytes(chain_id, idx),
                commit_sig.signature,
            )
        )
        tallied += val.voting_power
        if tallied > voting_power_needed:
            return out
    raise NotEnoughVotingPowerError(tallied, voting_power_needed)


def verify_triples_grouped(triples) -> None:
    """One merged signature check over (pub_key, sign_bytes, signature)
    triples collected from MANY commits (collect_commit_light), grouped
    per key type — the same grouping _verify_commit_batch applies
    within one commit. Raises InvalidCommitError on any failure with no
    index attribution: callers re-verify per commit for the precise
    error (light/client.py sequential window fallback)."""
    with trace.span(
        "batch_accumulate", sigs=len(triples), merged=True
    ):
        groups: dict = {}
        for pk, sb, sig in triples:
            if not supports_batch_verifier(pk):
                if not pk.verify_signature(sb, sig):
                    raise InvalidCommitError(
                        "wrong signature in merged batch"
                    )
                continue
            bv = groups.get(pk.type())
            if bv is None:
                bv = create_batch_verifier(pk, size_hint=len(triples))
                groups[pk.type()] = bv
            bv.add(pk, sb, sig)
        for bv in groups.values():
            ok, _bits = bv.verify()
            if not ok:
                raise InvalidCommitError("wrong signature in merged batch")


def _verify_basic(
    vals: Optional[ValidatorSet],
    commit: Optional[Commit],
    height: int,
    block_id: BlockID,
) -> None:
    """reference: types/validation.go:330-352."""
    if vals is None:
        raise InvalidCommitError("nil validator set")
    if commit is None:
        raise InvalidCommitError("nil commit")
    if vals.size() != len(commit.signatures):
        raise InvalidCommitError(
            f"invalid commit -- wrong set size: {vals.size()} vs "
            f"{len(commit.signatures)}"
        )
    if height != commit.height:
        raise InvalidCommitError(
            f"invalid commit -- wrong height: {height} vs {commit.height}"
        )
    if block_id != commit.block_id:
        raise InvalidCommitError(
            f"invalid commit -- wrong block ID: want {block_id}, "
            f"got {commit.block_id}"
        )


def _verify_commit_batch(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig: Callable[[CommitSig], bool],
    count_sig: Callable[[CommitSig], bool],
    count_all_signatures: bool,
    look_up_by_index: bool,
) -> None:
    """Span-wrapped shim: the accumulate loop AND the verifier drains
    run under one `batch_accumulate` span, so the tpu_dispatch spans
    opened by BatchVerifier.verify() nest inside it — the trace shape
    PERF.md needs to split host assembly from device time per commit."""
    with trace.span(
        "batch_accumulate",
        sigs=len(commit.signatures),
        height=commit.height,
    ):
        _verify_commit_batch_impl(
            chain_id, vals, commit, voting_power_needed,
            ignore_sig, count_sig, count_all_signatures, look_up_by_index,
        )


def _verify_commit_batch_impl(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig: Callable[[CommitSig], bool],
    count_sig: Callable[[CommitSig], bool],
    count_all_signatures: bool,
    look_up_by_index: bool,
) -> None:
    """reference: types/validation.go:152-262, extended for mixed-key
    validator sets (the BASELINE mixed ed25519/sr25519 stress shape):
    one batch verifier PER KEY TYPE, created lazily, so ed25519
    signatures ride the device path while other types use their own CPU
    batch verifiers. The reference's single-verifier form errors out of
    mixed sets (its BatchVerifier.Add rejects foreign key types with no
    fallback); grouping by type preserves its semantics for uniform
    sets and makes mixed sets first-class. A key type with no batch
    support at all (secp256k1) verifies inline."""
    tallied = 0
    seen_vals: dict[int, int] = {}
    # key type -> (verifier, [commit sig indexes added to it])
    groups: dict[str, tuple] = {}
    # key type -> (bound add or None-for-inline, bound index append)
    _adders: dict[str, tuple] = {}
    # one templated pass for all sign-bytes when every signature will
    # be checked (verify_commit): at 10k signatures the per-index
    # marshal is the dominant host cost (see Commit.sign_bytes_batch).
    # Early-exit variants (light/trusting stop at 2/3 and ignore nil
    # votes) encode lazily per index instead — still template-cached —
    # so no discarded rows are paid for.
    all_sign_bytes = (
        commit.sign_bytes_batch(chain_id) if count_all_signatures else None
    )
    for idx, commit_sig in enumerate(commit.signatures):
        if ignore_sig(commit_sig):
            continue
        if look_up_by_index:
            val = vals.validators[idx]
        else:
            val_idx, val = vals.get_by_address(
                commit_sig.validator_address
            )
            if val is None:
                continue
            if val_idx in seen_vals:
                raise InvalidCommitError(
                    f"double vote from {val.address.hex()} "
                    f"({seen_vals[val_idx]} and {idx})"
                )
            seen_vals[val_idx] = idx
        vote_sign_bytes = (
            all_sign_bytes[idx]
            if all_sign_bytes is not None
            else commit.vote_sign_bytes(chain_id, idx)
        )
        key_type = val.pub_key.type()
        # per-key-type dispatch cached: at 10k signatures the repeated
        # supports_batch_verifier() call and per-item bound-method
        # creation were a measurable slice of the assemble phase
        entry = _adders.get(key_type)
        if entry is None:
            if not supports_batch_verifier(val.pub_key):
                _adders[key_type] = (None, None)
            else:
                bv = create_batch_verifier(
                    val.pub_key, size_hint=len(commit.signatures)
                )
                idxs: list = []
                groups[key_type] = (bv, idxs)
                _adders[key_type] = (bv.add, idxs.append)
            entry = _adders[key_type]
        add_fn, idx_append = entry
        if add_fn is None:
            # no batch support for this type: verify inline
            if not val.pub_key.verify_signature(
                vote_sign_bytes, commit_sig.signature
            ):
                raise InvalidCommitError(
                    f"wrong signature (#{idx}): "
                    f"{commit_sig.signature.hex()}"
                )
        else:
            add_fn(val.pub_key, vote_sign_bytes, commit_sig.signature)
            idx_append(idx)
        if count_sig(commit_sig):
            tallied += val.voting_power
        if not count_all_signatures and tallied > voting_power_needed:
            break
    if tallied <= voting_power_needed:
        raise NotEnoughVotingPowerError(tallied, voting_power_needed)
    first_bad: Optional[int] = None
    for bv, batch_sig_idxs in groups.values():
        ok, valid_sigs = bv.verify()
        if ok:
            continue
        bad = [
            batch_sig_idxs[i]
            for i, sig_ok in enumerate(valid_sigs)
            if not sig_ok
        ]
        if not bad:
            raise RuntimeError(
                "BUG: batch verification failed with no invalid signatures"
            )
        if first_bad is None or bad[0] < first_bad:
            first_bad = bad[0]
    if first_bad is not None:
        raise InvalidCommitError(
            f"wrong signature (#{first_bad}): "
            f"{commit.signatures[first_bad].signature.hex()}"
        )


def _verify_commit_single(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig: Callable[[CommitSig], bool],
    count_sig: Callable[[CommitSig], bool],
    count_all_signatures: bool,
    look_up_by_index: bool,
) -> None:
    """reference: types/validation.go:265-328."""
    tallied = 0
    seen_vals: dict[int, int] = {}
    for idx, commit_sig in enumerate(commit.signatures):
        if ignore_sig(commit_sig):
            continue
        if look_up_by_index:
            val = vals.validators[idx]
        else:
            val_idx, val = vals.get_by_address(
                commit_sig.validator_address
            )
            if val is None:
                continue
            if val_idx in seen_vals:
                raise InvalidCommitError(
                    f"double vote from {val.address.hex()} "
                    f"({seen_vals[val_idx]} and {idx})"
                )
            seen_vals[val_idx] = idx
        vote_sign_bytes = commit.vote_sign_bytes(chain_id, idx)
        if not val.pub_key.verify_signature(
            vote_sign_bytes, commit_sig.signature
        ):
            raise InvalidCommitError(
                f"wrong signature (#{idx}): "
                f"{commit_sig.signature.hex()}"
            )
        if count_sig(commit_sig):
            tallied += val.voting_power
        if not count_all_signatures and tallied > voting_power_needed:
            return
    if tallied <= voting_power_needed:
        raise NotEnoughVotingPowerError(tallied, voting_power_needed)
