"""Commit verification — the framework's north-star hot path.

Mirrors types/validation.go exactly: VerifyCommit (:25, checks ALL sigs
for incentivization), VerifyCommitLight (:59, stops at 2/3),
VerifyCommitLightTrusting (:94, fraction of a *trusted* set, lookup by
address), and the batch/single pair (:152/:265). The batch path packs a
whole Commit's (pubkey, sign-bytes, signature) triples into one
crypto.batch verifier — on TPU that is a single device program over the
padded batch (tendermint_tpu.ops.ed25519_kernel), sharded across the
mesh for large validator sets (tendermint_tpu.parallel.sharding).

Every path here consults the process-wide verified-signature cache
(crypto.sigcache) BEFORE batch assembly and populates it on success:
only cache misses are assembled, so a LastCommit whose precommits were
gossip-verified re-verifies with zero crypto calls, and device buckets
pad to the real miss count. TM_TPU_NO_SIGCACHE=1 restores the uncached
behavior exactly (same errors, same tallies — just slower).

The WARM path additionally does zero encoding and (near-)zero per-vote
Python work (PERF.md "Warm path"): sign-bytes come from the commit-
scoped memo (Commit.sign_bytes_batch / vote_sign_bytes), the cache
scan is one bulk set-intersection (sigcache.seen_keys_bulk) instead of
a per-triple probe loop, tallies are masked-numpy sums / prefix-sums
over ValidatorSet.powers_array(), and a commit that verified fully
before short-circuits to the tally via the commit-level memo
(sigcache.seen_commit) in O(1) probes. Every vectorized plan computes
the SAME processed-index set and error as the scalar reference loop
(_verify_commit_batch_scalar — kept as the fallback for hostile
flag encodings and locked byte-identical by the property tests in
tests/test_warmpath.py); the memo-soundness argument is machine-
checked by `scripts/lint.py --memo-audit` (docs/static_analysis.md).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from ..crypto import sigcache
from ..crypto.batch import (
    create_batch_verifier,
    drain_and_cache,
    supports_batch_verifier,
)
from ..libs import trace
from .block_id import BlockID
from .commit import (
    BLOCK_ID_FLAG_ABSENT,
    BLOCK_ID_FLAG_COMMIT,
    Commit,
    CommitSig,
)
from .validator import ValidatorSet

__all__ = [
    "BATCH_VERIFY_THRESHOLD",
    "Fraction",
    "NotEnoughVotingPowerError",
    "InvalidCommitError",
    "collect_commit_light",
    "verify_commit",
    "verify_commit_light",
    "verify_commit_light_bulk",
    "verify_commit_light_trusting",
    "verify_triples_grouped",
]

BATCH_VERIFY_THRESHOLD = 2  # reference: types/validation.go:12


@dataclass(frozen=True)
class Fraction:
    """Trust level, e.g. 1/3 (reference: libs/math/fraction.go)."""

    numerator: int
    denominator: int

    def validate(self) -> None:
        if self.denominator == 0:
            raise ValueError("fraction has zero denominator")


class InvalidCommitError(ValueError):
    pass


class NotEnoughVotingPowerError(InvalidCommitError):
    def __init__(self, got: int, needed: int) -> None:
        super().__init__(
            f"invalid commit -- insufficient voting power: got {got}, "
            f"needed more than {needed}"
        )
        self.got = got
        self.needed = needed


def _should_batch_verify(vals: ValidatorSet, commit: Commit) -> bool:
    return len(
        commit.signatures
    ) >= BATCH_VERIFY_THRESHOLD and supports_batch_verifier(
        vals.get_proposer().pub_key
    )


def verify_commit(
    chain_id: str,
    vals: ValidatorSet,
    block_id: BlockID,
    height: int,
    commit: Commit,
) -> None:
    """+2/3 signed, verifying ALL signatures (incentivization needs the
    full bitmap — reference: types/validation.go:18-51)."""
    _verify_basic(vals, commit, height, block_id)
    voting_power_needed = vals.total_voting_power() * 2 // 3
    ignore = lambda c: c.is_absent()  # noqa: E731
    count = lambda c: c.is_for_block()  # noqa: E731
    if _should_batch_verify(vals, commit):
        _verify_commit_batch(
            chain_id, vals, commit, voting_power_needed,
            ignore, count, True, True, vector_tally=True,
        )
    else:
        _verify_commit_single(
            chain_id, vals, commit, voting_power_needed,
            ignore, count, True, True,
        )


def verify_commit_light(
    chain_id: str,
    vals: ValidatorSet,
    block_id: BlockID,
    height: int,
    commit: Commit,
) -> None:
    """+2/3 signed, early exit once the tally crosses 2/3
    (reference: types/validation.go:55-85)."""
    _verify_basic(vals, commit, height, block_id)
    voting_power_needed = vals.total_voting_power() * 2 // 3
    ignore = lambda c: not c.is_for_block()  # noqa: E731
    count = lambda c: True  # noqa: E731
    if _should_batch_verify(vals, commit):
        _verify_commit_batch(
            chain_id, vals, commit, voting_power_needed,
            ignore, count, False, True, vector_tally=True,
        )
    else:
        _verify_commit_single(
            chain_id, vals, commit, voting_power_needed,
            ignore, count, False, True,
        )


def verify_commit_light_trusting(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    trust_level: Fraction,
) -> None:
    """trust_level (e.g. 1/3) of a TRUSTED validator set signed; lookup
    by address since sets needn't match
    (reference: types/validation.go:87-131)."""
    if vals is None:
        raise InvalidCommitError("nil validator set")
    trust_level.validate()
    if commit is None:
        raise InvalidCommitError("nil commit")
    total_mul = vals.total_voting_power() * trust_level.numerator
    if total_mul >= 1 << 63:
        raise InvalidCommitError(
            "int64 overflow while calculating voting power needed"
        )
    voting_power_needed = total_mul // trust_level.denominator
    ignore = lambda c: not c.is_for_block()  # noqa: E731
    count = lambda c: True  # noqa: E731
    if _should_batch_verify(vals, commit):
        _verify_commit_batch(
            chain_id, vals, commit, voting_power_needed,
            ignore, count, False, False, vector_tally=True,
        )
    else:
        _verify_commit_single(
            chain_id, vals, commit, voting_power_needed,
            ignore, count, False, False,
        )


def collect_commit_light(
    chain_id: str,
    vals: ValidatorSet,
    block_id: BlockID,
    height: int,
    commit: Commit,
) -> list:
    """verify_commit_light's host-side half: run every non-signature
    check (set size, height, block ID, 2/3 tally with the same
    early-exit) and return the (pub_key, sign_bytes, signature)
    triples verify_commit_light would have signature-checked — without
    checking them. Callers fold triples from MANY commits into one
    device batch (the light client's sequential group sync,
    light/client.py); any triple failing there must be re-verified
    per-commit for the reference's exact error. Mirrors the tally
    semantics of types/validation.go:55-85."""
    _verify_basic(vals, commit, height, block_id)
    voting_power_needed = vals.total_voting_power() * 2 // 3
    flags = commit.block_id_flags_array()
    if flags is not None:
        # prefix-sum form of the early-exit tally (the same
        # _prefix_crossing plan as the vectorized verify_commit_light):
        # the crossing index is the exact vote the reference loop below
        # returns after, so the collected triples are identical — and
        # the per-index encodes hit the commit-scoped sign-bytes memo
        powers = vals.powers_array()
        tallied, end = _prefix_crossing(
            np.where(flags == BLOCK_ID_FLAG_COMMIT, powers, 0),
            voting_power_needed,
        )
        if end is None:
            raise NotEnoughVotingPowerError(tallied, voting_power_needed)
        validators = vals.validators
        signatures = commit.signatures
        return [
            (
                validators[i].pub_key,
                commit.vote_sign_bytes(chain_id, i),
                signatures[i].signature,
            )
            for i in np.flatnonzero(
                flags[:end] == BLOCK_ID_FLAG_COMMIT
            ).tolist()
        ]
    # scalar reference loop (kept for hostile flag encodings); lazy
    # per-index encode: this early-exit variant skips nil votes and
    # stops at 2/3, so a full precompute would pay for rows it discards
    tallied = 0
    out = []
    for idx, commit_sig in enumerate(commit.signatures):
        if not commit_sig.is_for_block():
            continue
        # look_up_by_index semantics (same-set verification)
        val = vals.validators[idx]
        out.append(
            (
                val.pub_key,
                commit.vote_sign_bytes(chain_id, idx),
                commit_sig.signature,
            )
        )
        tallied += val.voting_power
        if tallied > voting_power_needed:
            return out
    raise NotEnoughVotingPowerError(tallied, voting_power_needed)


def verify_triples_grouped(triples) -> None:
    """One merged signature check over (pub_key, sign_bytes, signature)
    triples collected from MANY commits (collect_commit_light), grouped
    per key type — the same grouping _verify_commit_batch applies
    within one commit. Triples already proven by the verified-signature
    cache (crypto.sigcache) are skipped before assembly; the rest
    populate it on success, so the per-commit re-verify after a merged
    failure only pays for the actually-bad commit. Raises
    InvalidCommitError on any failure with no index attribution:
    callers re-verify per commit for the precise error
    (light/client.py sequential window fallback)."""
    with trace.span(
        "batch_accumulate", sigs=len(triples), merged=True
    ):
        use_cache = sigcache.enabled()
        hits = misses = 0
        # key type -> [(pk, sign_bytes, signature, cache key)]: assembly
        # is deferred so each group's size_hint is its OWN miss count —
        # previously every group got size_hint=len(triples), so in mixed
        # sets each device bucket padded to the merged total
        pending: dict = {}
        # one bulk set-intersection over the whole merged window
        # replaces the per-triple generation probes (the light client's
        # 32-hop sequential windows are ~5k triples)
        keys: list = []
        hit_set: set = set()
        if use_cache:
            keys = [
                sigcache.key_for(pk.bytes(), sb, sig)
                for pk, sb, sig in triples
            ]
            hit_set = sigcache.seen_keys_bulk(keys)
        for n, (pk, sb, sig) in enumerate(triples):
            ckey = None
            if use_cache:
                ckey = keys[n]
                if ckey in hit_set:
                    hits += 1
                    continue
                misses += 1
            if not supports_batch_verifier(pk):
                if not pk.verify_signature(sb, sig):
                    if use_cache:  # keep the scanned hit/miss counts
                        sigcache.observe(hits, misses)
                    raise InvalidCommitError(
                        "wrong signature in merged batch"
                    )
                if ckey is not None:
                    sigcache.add_key(ckey)
                continue
            pending.setdefault(pk.type(), []).append((pk, sb, sig, ckey))
        if use_cache:
            sigcache.observe(hits, misses)
            trace.add_attrs(sigcache_hits=hits, sigcache_misses=misses)
        for items in pending.values():
            bv = create_batch_verifier(items[0][0], size_hint=len(items))
            for pk, sb, sig, _ckey in items:
                bv.add(pk, sb, sig)
            ok, _bits = drain_and_cache(bv, [it[3] for it in items])
            if not ok:
                raise InvalidCommitError("wrong signature in merged batch")


def verify_commit_light_bulk(chain_id: str, rows) -> None:
    """One sigcache-aware pass over M commits' light verifications —
    the fleet-serving form of verify_commit_light. `rows` is a
    sequence of (vals, block_id, height, commit), verified in order.

    Extends the PR-7 warm machinery ACROSS commits instead of within
    one: each row first probes the commit-level memo (the SAME
    `_commit_memo_key` verify_commit_light's vectorized path writes,
    so the two paths warm each other) — a warm fleet pass is M O(1)
    probes plus M basic checks, zero key building and zero crypto.
    Misses run collect_commit_light (the reference tally with its
    exact NotEnoughVotingPowerError / _verify_basic errors) and the
    collected triples from ALL cold commits are proven in ONE merged
    call (verify_triples_grouped: one bulk sigcache set-intersection,
    one grouped batch verify); only then is each cold commit's memo
    recorded. A signature failure raises InvalidCommitError with no
    index attribution — callers needing the reference's exact
    per-commit error re-verify per commit (the same contract as
    verify_triples_grouped, used by light/client.py's window
    fallback)."""
    rows = list(rows)
    with trace.span("verify_commit_light_bulk", commits=len(rows)):
        use_memo = sigcache.enabled() and sigcache.commit_memo_enabled()
        triples: list = []
        cold_keys: list = []
        hits = 0
        for vals, block_id, height, commit in rows:
            _verify_basic(vals, commit, height, block_id)
            ckey = None
            if use_memo:
                needed = vals.total_voting_power() * 2 // 3
                ckey = _commit_memo_key(
                    chain_id, vals, commit, needed, False, True,
                    vals.powers_array(),
                )
                if sigcache.seen_commit(ckey):
                    hits += 1
                    continue
            triples.extend(
                collect_commit_light(
                    chain_id, vals, block_id, height, commit
                )
            )
            if ckey is not None:
                cold_keys.append(ckey)
        if use_memo:
            trace.add_attrs(
                sigcache_commit_hits=hits, commits_cold=len(cold_keys)
            )
        if triples:
            verify_triples_grouped(triples)
        for ckey in cold_keys:
            sigcache.add_commit(ckey)


def _verify_basic(
    vals: Optional[ValidatorSet],
    commit: Optional[Commit],
    height: int,
    block_id: BlockID,
) -> None:
    """reference: types/validation.go:330-352."""
    if vals is None:
        raise InvalidCommitError("nil validator set")
    if commit is None:
        raise InvalidCommitError("nil commit")
    if vals.size() != len(commit.signatures):
        raise InvalidCommitError(
            f"invalid commit -- wrong set size: {vals.size()} vs "
            f"{len(commit.signatures)}"
        )
    if height != commit.height:
        raise InvalidCommitError(
            f"invalid commit -- wrong height: {height} vs {commit.height}"
        )
    if block_id != commit.block_id:
        raise InvalidCommitError(
            f"invalid commit -- wrong block ID: want {block_id}, "
            f"got {commit.block_id}"
        )


def _verify_commit_batch(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig: Callable[[CommitSig], bool],
    count_sig: Callable[[CommitSig], bool],
    count_all_signatures: bool,
    look_up_by_index: bool,
    vector_tally: bool = False,
) -> None:
    """Span-wrapped shim: the accumulate loop AND the verifier drains
    run under one `batch_accumulate` span, so the tpu_dispatch spans
    opened by BatchVerifier.verify() nest inside it — the trace shape
    PERF.md needs to split host assembly from device time per commit."""
    with trace.span(
        "batch_accumulate",
        sigs=len(commit.signatures),
        height=commit.height,
    ):
        _verify_commit_batch_impl(
            chain_id, vals, commit, voting_power_needed,
            ignore_sig, count_sig, count_all_signatures, look_up_by_index,
            vector_tally,
        )


def _verify_commit_batch_impl(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig: Callable[[CommitSig], bool],
    count_sig: Callable[[CommitSig], bool],
    count_all_signatures: bool,
    look_up_by_index: bool,
    vector_tally: bool = False,
) -> None:
    """reference: types/validation.go:152-262, extended for mixed-key
    validator sets (the BASELINE mixed ed25519/sr25519 stress shape):
    one batch verifier PER KEY TYPE so ed25519 signatures ride the
    device path while other types use their own CPU batch verifiers.
    The reference's single-verifier form errors out of mixed sets (its
    BatchVerifier.Add rejects foreign key types with no fallback);
    grouping by type preserves its semantics for uniform sets and makes
    mixed sets first-class. A key type with no batch support at all
    (secp256k1) verifies inline.

    `vector_tally=True` asserts that ignore_sig/count_sig are the
    STANDARD predicates for this (count_all_signatures,
    look_up_by_index) combination — absent-skip/commit-count for
    verify_commit, for-block-only/count-all for the light and trusting
    variants — and routes through the vectorized plans in
    _verify_commit_batch_vector, which compute the same processed-index
    set, tally, and errors as the scalar reference loop below (pinned
    by the property tests in tests/test_warmpath.py). A commit whose
    BlockIDFlags don't fit uint8 (hostile from_proto input) falls back
    to the scalar loop so the failure surfaces as the reference
    InvalidCommitError."""
    if vector_tally:
        flags = commit.block_id_flags_array()
        if flags is not None:
            _verify_commit_batch_vector(
                chain_id, vals, commit, voting_power_needed,
                count_all_signatures, look_up_by_index, flags,
            )
            return
    _verify_commit_batch_scalar(
        chain_id, vals, commit, voting_power_needed,
        ignore_sig, count_sig, count_all_signatures, look_up_by_index,
    )


def _prefix_crossing(masked_powers, voting_power_needed: int):
    """(tallied, end) of the reference early-exit scan over
    `masked_powers` — the per-position powers the scalar loop would ADD
    (zeros where it skips). The reference breaks AFTER the vote whose
    running total crosses the threshold, i.e. at the first index where
    the prefix sum exceeds it; `end` is that index + 1 (the exclusive
    scan bound), or None when the whole array is scanned without
    crossing. Single home for the cum/argmax subtlety shared by the
    vectorized light/trusting plans and collect_commit_light."""
    cum = masked_powers.cumsum()
    total = int(cum[-1]) if cum.size else 0
    if total > voting_power_needed:
        cross = int(np.argmax(cum > voting_power_needed))
        return int(cum[cross]), cross + 1
    return total, None


def _commit_memo_key(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    count_all_signatures: bool,
    look_up_by_index: bool,
    powers,
) -> tuple:
    """The commit-level sigcache key (crypto/sigcache seen_commit /
    add_commit): binds the verification mode, threshold, a content-
    identity token per commit and validator set, the process-wide
    validator-mutation epoch (so an in-place pub_key/address swap —
    which moves neither fingerprint token nor the powers bytes — can
    never serve a stale success; types/validator.py _VAL_MUT_EPOCH),
    and the live powers bytes as defense in depth. Single home shared
    with bench_commit_warm_breakdown's commit_probe phase so the
    measured probe can't drift from the production key shape."""
    from .validator import _VAL_MUT_EPOCH

    return (
        "commit-memo",
        chain_id,
        count_all_signatures,
        look_up_by_index,
        voting_power_needed,
        commit.fingerprint_token(),
        vals.fingerprint_token(),
        _VAL_MUT_EPOCH[0],
        powers.tobytes(),
    )


def _verify_commit_batch_vector(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    count_all_signatures: bool,
    look_up_by_index: bool,
    flags,
) -> None:
    """The warm-path engine: zero encoding (commit-scoped sign-bytes
    memo), one bulk cache probe (sigcache.seen_keys_bulk) instead of a
    per-triple loop, a masked-sum / prefix-sum tally instead of
    per-vote predicate calls, and an O(1) commit-level short-circuit
    (sigcache.seen_commit) for a commit this process fully verified
    before. Behavior — processed indexes, early-exit points, error
    strings — is byte-identical to _verify_commit_batch_scalar by
    construction and by property test:

    - verify_commit (count_all, by index): processes every non-absent
      index; tally = sum of powers where flag == COMMIT.
    - verify_commit_light (early exit, by index): the reference loop
      counts every for-block vote in index order and breaks after the
      vote that crosses 2/3 — exactly the first index where the
      prefix-sum of COMMIT-masked powers exceeds the threshold. The
      processed set is the for-block prefix through that crossing.
    - verify_commit_light_trusting (early exit, by address): same
      prefix-sum over powers resolved through the trusted set's address
      index (missing addresses contribute 0, exactly like the
      reference's skip). Duplicate addresses can only INFLATE the
      prefix-sum, so the computed crossing k never lies beyond the
      reference's scan end: a duplicate at index j <= k is re-detected
      by the per-index replay below and raises the reference's double-
      vote error; a duplicate at j > k was never reached by the
      reference loop either, and then the prefix through k is
      duplicate-free so its sums agree exactly.

    Only the inline (non-batchable key) failure path accounts cache
    metrics differently: the scalar loop observes the counts scanned so
    far, this path observes the full plan's counts up front. Errors and
    verification work are identical."""
    use_cache = sigcache.enabled()
    sigs = commit.signatures
    powers = vals.powers_array()

    # --- the plan: processed indexes (ascending) + precomputed tally
    if count_all_signatures:
        tallied = int(powers[flags == BLOCK_ID_FLAG_COMMIT].sum())
        idx_list = np.flatnonzero(flags != BLOCK_ID_FLAG_ABSENT).tolist()
    elif look_up_by_index:
        tallied, end = _prefix_crossing(
            np.where(flags == BLOCK_ID_FLAG_COMMIT, powers, 0),
            voting_power_needed,
        )
        idx_list = np.flatnonzero(
            (flags if end is None else flags[:end]) == BLOCK_ID_FLAG_COMMIT
        ).tolist()
    else:
        fb = np.flatnonzero(flags == BLOCK_ID_FLAG_COMMIT)
        addr_index = vals._addr_index
        vi = np.fromiter(
            (
                addr_index.get(sigs[i].validator_address, -1)
                for i in fb.tolist()
            ),
            dtype=np.int64,
            count=fb.size,
        )
        tallied, end = _prefix_crossing(
            np.where(vi >= 0, powers[np.maximum(vi, 0)], 0),
            voting_power_needed,
        )
        idx_list = (fb if end is None else fb[:end]).tolist()

    # --- commit-level memo: a commit this process fully verified
    # before, in this mode, against this exact set composition and
    # these exact live powers, short-circuits to the (deterministic)
    # success in O(1) probes. Failures are never recorded, the token
    # components die with any mutation, and TM_TPU_NO_SIGCACHE /
    # TM_TPU_NO_COMMIT_MEMO disable the whole consult.
    ckey_commit = None
    if use_cache and sigcache.commit_memo_enabled():
        ckey_commit = _commit_memo_key(
            chain_id, vals, commit, voting_power_needed,
            count_all_signatures, look_up_by_index, powers,
        )
        if sigcache.seen_commit(ckey_commit):
            trace.add_attrs(sigcache_commit_hit=True, sigs_warm=len(idx_list))
            return

    # key type -> [(pub_key, sign_bytes, signature, commit idx, cache
    # key)]: the cache misses awaiting batch verification
    pending: dict[str, list] = {}
    # key type -> supports_batch_verifier (cached: at 10k signatures the
    # repeated registry lookup was a measurable slice of the scan)
    batchable: dict[str, bool] = {}

    if look_up_by_index:
        validators = vals.validators
        if count_all_signatures:
            rows = commit.sign_bytes_batch(chain_id)
        else:
            # early-exit variant: encode only the processed prefix,
            # lazily and memoized — no discarded rows are paid for
            rows = None
            vsb = commit.vote_sign_bytes
        misses = idx_list
        hits_n = 0
        if use_cache:
            pkb = vals.pubkeys_bytes()
            if rows is not None:
                # rows is None exactly at absent indexes, i.e. exactly
                # the complement of idx_list — the zip form skips three
                # indexed lookups per signature vs iterating idx_list
                keys = [
                    (b, r, cs.signature)
                    for b, r, cs in zip(pkb, rows, sigs)
                    if r is not None
                ]
            else:
                keys = [
                    (pkb[i], vsb(chain_id, i), sigs[i].signature)
                    for i in idx_list
                ]
            hit_set = sigcache.seen_keys_bulk(keys)
            hits_n = len(hit_set)
            if hits_n == len(keys):
                misses = []
            else:
                misses = [
                    i
                    for i, k in zip(idx_list, keys)
                    if k not in hit_set
                ]
            sigcache.observe(hits_n, len(misses))
            trace.add_attrs(
                sigcache_hits=hits_n, sigcache_misses=len(misses)
            )
        for i in misses:
            pub_key = validators[i].pub_key
            sb = rows[i] if rows is not None else vsb(chain_id, i)
            sig = sigs[i].signature
            key_type = pub_key.type()
            can_batch = batchable.get(key_type)
            if can_batch is None:
                can_batch = batchable[key_type] = supports_batch_verifier(
                    pub_key
                )
            if not can_batch:
                if not pub_key.verify_signature(sb, sig):
                    raise InvalidCommitError(
                        f"wrong signature (#{i}): {sig.hex()}"
                    )
                if use_cache:
                    sigcache.add_key((pub_key.bytes(), sb, sig))
            else:
                pending.setdefault(key_type, []).append(
                    (
                        pub_key, sb, sig, i,
                        (pub_key.bytes(), sb, sig) if use_cache else None,
                    )
                )
    else:
        # trusting: per-index replay of the reference body over the
        # precomputed prefix — the double-vote ordering machinery stays
        # scalar, only ignore/count/early-exit bookkeeping is gone
        _seen_key = sigcache.seen_key
        hits_n = misses_n = 0
        seen_vals: dict[int, int] = {}
        for idx in idx_list:
            commit_sig = sigs[idx]
            val_idx, val = vals.get_by_address(commit_sig.validator_address)
            if val is None:
                continue
            if val_idx in seen_vals:
                raise InvalidCommitError(
                    f"double vote from {val.address.hex()} "
                    f"({seen_vals[val_idx]} and {idx})"
                )
            seen_vals[val_idx] = idx
            vote_sign_bytes = commit.vote_sign_bytes(chain_id, idx)
            pub_key = val.pub_key
            ckey = None
            if use_cache:
                ckey = (
                    pub_key.bytes(), vote_sign_bytes, commit_sig.signature
                )
                if _seen_key(ckey):
                    hits_n += 1
                    continue
                misses_n += 1
            key_type = pub_key.type()
            can_batch = batchable.get(key_type)
            if can_batch is None:
                can_batch = batchable[key_type] = supports_batch_verifier(
                    pub_key
                )
            if not can_batch:
                if not pub_key.verify_signature(
                    vote_sign_bytes, commit_sig.signature
                ):
                    if use_cache:  # keep the scanned hit/miss counts
                        sigcache.observe(hits_n, misses_n)
                    raise InvalidCommitError(
                        f"wrong signature (#{idx}): "
                        f"{commit_sig.signature.hex()}"
                    )
                if ckey is not None:
                    sigcache.add_key(ckey)
            else:
                pending.setdefault(key_type, []).append(
                    (
                        pub_key, vote_sign_bytes, commit_sig.signature,
                        idx, ckey,
                    )
                )
        if use_cache:
            sigcache.observe(hits_n, misses_n)
            trace.add_attrs(sigcache_hits=hits_n, sigcache_misses=misses_n)

    if tallied <= voting_power_needed:
        raise NotEnoughVotingPowerError(tallied, voting_power_needed)
    _drain_pending(commit, pending)
    if ckey_commit is not None:
        sigcache.add_commit(ckey_commit)


def _verify_commit_batch_scalar(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig: Callable[[CommitSig], bool],
    count_sig: Callable[[CommitSig], bool],
    count_all_signatures: bool,
    look_up_by_index: bool,
) -> None:
    """The reference scan loop (types/validation.go:152-262): per-vote
    predicates, incremental tally, early exit by running total. The
    vectorized plans above must stop at the same vote and raise the
    same errors as this loop — it is both the fallback for hostile
    flag encodings and the oracle the property tests compare against.

    Cache-aware batch assembly: each triple is first checked against
    the verified-signature cache (crypto.sigcache); hits skip crypto
    entirely and only MISSES are assembled, deferred until after the
    scan so every group's batch verifier gets size_hint = its own miss
    count — the padded device bucket shrinks to the real work instead
    of the whole commit (and, per key type, to the group rather than
    the merged total)."""
    use_cache = sigcache.enabled()
    _seen_key = sigcache.seen_key  # hoisted: called once per signature
    tallied = 0
    hits = misses = 0
    seen_vals: dict[int, int] = {}
    # key type -> [(pub_key, sign_bytes, signature, commit idx, cache
    # key)]: the cache misses awaiting batch verification
    pending: dict[str, list] = {}
    # key type -> supports_batch_verifier
    batchable: dict[str, bool] = {}
    # one templated pass for all sign-bytes when every signature will
    # be checked (verify_commit); early-exit variants encode lazily per
    # index (memoized) so no discarded rows are paid for
    all_sign_bytes = (
        commit.sign_bytes_batch(chain_id) if count_all_signatures else None
    )
    signatures = commit.signatures
    for idx, commit_sig in enumerate(signatures):
        if ignore_sig(commit_sig):
            continue
        if look_up_by_index:
            val = vals.validators[idx]
        else:
            val_idx, val = vals.get_by_address(
                commit_sig.validator_address
            )
            if val is None:
                continue
            if val_idx in seen_vals:
                raise InvalidCommitError(
                    f"double vote from {val.address.hex()} "
                    f"({seen_vals[val_idx]} and {idx})"
                )
            seen_vals[val_idx] = idx
        vote_sign_bytes = (
            all_sign_bytes[idx]
            if all_sign_bytes is not None
            else commit.vote_sign_bytes(chain_id, idx)
        )
        pub_key = val.pub_key
        ckey = None
        if use_cache:
            # inline sigcache.key_for — the tuple IS the key, and the
            # call overhead is measurable at 10k signatures
            ckey = (
                pub_key.bytes(), vote_sign_bytes, commit_sig.signature
            )
            if _seen_key(ckey):
                hits += 1
                if count_sig(commit_sig):
                    tallied += val.voting_power
                if (
                    not count_all_signatures
                    and tallied > voting_power_needed
                ):
                    break
                continue
            misses += 1
        key_type = pub_key.type()
        can_batch = batchable.get(key_type)
        if can_batch is None:
            can_batch = batchable[key_type] = supports_batch_verifier(
                pub_key
            )
        if not can_batch:
            # no batch support for this type: verify inline
            if not pub_key.verify_signature(
                vote_sign_bytes, commit_sig.signature
            ):
                if use_cache:  # keep the scanned hit/miss counts
                    sigcache.observe(hits, misses)
                raise InvalidCommitError(
                    f"wrong signature (#{idx}): "
                    f"{commit_sig.signature.hex()}"
                )
            if ckey is not None:
                sigcache.add_key(ckey)
        else:
            pending.setdefault(key_type, []).append(
                (pub_key, vote_sign_bytes, commit_sig.signature, idx, ckey)
            )
        if count_sig(commit_sig):
            tallied += val.voting_power
        if not count_all_signatures and tallied > voting_power_needed:
            break
    if use_cache:
        sigcache.observe(hits, misses)
        trace.add_attrs(sigcache_hits=hits, sigcache_misses=misses)
    if tallied <= voting_power_needed:
        raise NotEnoughVotingPowerError(tallied, voting_power_needed)
    _drain_pending(commit, pending)


def _drain_pending(commit: Commit, pending: dict) -> None:
    """Drain the per-key-type miss batches, populating the cache for
    proven triples, and raise the reference error for the LOWEST bad
    commit index across groups."""
    first_bad: Optional[int] = None
    for items in pending.values():
        bv = create_batch_verifier(items[0][0], size_hint=len(items))
        for pub_key, sb, sig, _idx, _ckey in items:
            bv.add(pub_key, sb, sig)
        ok, valid_sigs = drain_and_cache(bv, [it[4] for it in items])
        if ok:
            continue
        bad = [
            items[i][3]
            for i, sig_ok in enumerate(valid_sigs)
            if not sig_ok
        ]
        if not bad:
            raise RuntimeError(
                "BUG: batch verification failed with no invalid signatures"
            )
        if first_bad is None or bad[0] < first_bad:
            first_bad = bad[0]
    if first_bad is not None:
        raise InvalidCommitError(
            f"wrong signature (#{first_bad}): "
            f"{commit.signatures[first_bad].signature.hex()}"
        )


def _verify_commit_single(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig: Callable[[CommitSig], bool],
    count_sig: Callable[[CommitSig], bool],
    count_all_signatures: bool,
    look_up_by_index: bool,
) -> None:
    """reference: types/validation.go:265-328. Consults the verified-
    signature cache before each verify and populates it on success, so
    the single path and the batch path warm each other."""
    use_cache = sigcache.enabled()
    tallied = 0
    hits = misses = 0
    seen_vals: dict[int, int] = {}
    for idx, commit_sig in enumerate(commit.signatures):
        if ignore_sig(commit_sig):
            continue
        if look_up_by_index:
            val = vals.validators[idx]
        else:
            val_idx, val = vals.get_by_address(
                commit_sig.validator_address
            )
            if val is None:
                continue
            if val_idx in seen_vals:
                raise InvalidCommitError(
                    f"double vote from {val.address.hex()} "
                    f"({seen_vals[val_idx]} and {idx})"
                )
            seen_vals[val_idx] = idx
        vote_sign_bytes = commit.vote_sign_bytes(chain_id, idx)
        if use_cache:
            ckey = (
                val.pub_key.bytes(), vote_sign_bytes, commit_sig.signature
            )
            if sigcache.seen_key(ckey):
                hits += 1
            else:
                misses += 1
                if not val.pub_key.verify_signature(
                    vote_sign_bytes, commit_sig.signature
                ):
                    sigcache.observe(hits, misses)
                    raise InvalidCommitError(
                        f"wrong signature (#{idx}): "
                        f"{commit_sig.signature.hex()}"
                    )
                sigcache.add_key(ckey)
        elif not val.pub_key.verify_signature(
            vote_sign_bytes, commit_sig.signature
        ):
            raise InvalidCommitError(
                f"wrong signature (#{idx}): "
                f"{commit_sig.signature.hex()}"
            )
        if count_sig(commit_sig):
            tallied += val.voting_power
        if not count_all_signatures and tallied > voting_power_needed:
            sigcache.observe(hits, misses)
            return
    sigcache.observe(hits, misses)
    if tallied <= voting_power_needed:
        raise NotEnoughVotingPowerError(tallied, voting_power_needed)
