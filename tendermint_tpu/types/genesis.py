"""GenesisDoc — the chain's consensus-critical birth certificate.

Reference: types/genesis.go (GenesisDoc :37-60, ValidateAndComplete :75,
GenesisDocFromFile :140). JSON is the canonical on-disk form, matching
the reference's genesis.json.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import List, Optional

from ..crypto.keys import PubKey, pubkey_from_type_and_bytes
from .params import ConsensusParams
from .timestamp import from_rfc3339, now_ns, to_rfc3339
from .validator import Validator, ValidatorSet

__all__ = ["GenesisValidator", "GenesisDoc", "MAX_CHAIN_ID_LEN"]

MAX_CHAIN_ID_LEN = 50  # reference: types/genesis.go:27


@dataclass
class GenesisValidator:
    pub_key: PubKey
    power: int
    name: str = ""
    address: bytes = b""

    def __post_init__(self) -> None:
        if not self.address:
            self.address = self.pub_key.address()


@dataclass
class GenesisDoc:
    chain_id: str
    genesis_time_ns: int = 0
    initial_height: int = 1
    consensus_params: Optional[ConsensusParams] = field(
        default_factory=ConsensusParams
    )
    validators: List[GenesisValidator] = field(default_factory=list)
    app_hash: bytes = b""
    app_state: bytes = b""  # raw JSON passed to the app at InitChain

    def validate_and_complete(self) -> None:
        """reference: types/genesis.go:75-130."""
        if not self.chain_id:
            raise ValueError("genesis doc must include non-empty chain_id")
        if len(self.chain_id) > MAX_CHAIN_ID_LEN:
            raise ValueError(
                f"chain_id in genesis doc is too long (max: "
                f"{MAX_CHAIN_ID_LEN})"
            )
        if self.initial_height < 0:
            raise ValueError("initial_height cannot be negative")
        if self.initial_height == 0:
            self.initial_height = 1
        if self.consensus_params is None:
            self.consensus_params = ConsensusParams()
        else:
            self.consensus_params.validate()
        for i, v in enumerate(self.validators):
            if v.power == 0:
                raise ValueError(
                    f"the genesis file cannot contain validators with "
                    f"no voting power: {v.name or i}"
                )
            if v.address and v.pub_key.address() != v.address:
                raise ValueError(
                    f"incorrect address for validator {v.name or i}"
                )
        if self.genesis_time_ns == 0:
            self.genesis_time_ns = now_ns()

    def validator_set(self) -> ValidatorSet:
        return ValidatorSet(
            [
                Validator(pub_key=v.pub_key, voting_power=v.power)
                for v in self.validators
            ]
        )

    # -- JSON round-trip (canonical on-disk form) --

    def to_json(self) -> str:
        doc = {
            "genesis_time": to_rfc3339(self.genesis_time_ns),
            "chain_id": self.chain_id,
            "initial_height": str(self.initial_height),
            "consensus_params": {
                "block": {
                    "max_bytes": str(self.consensus_params.block.max_bytes),
                    "max_gas": str(self.consensus_params.block.max_gas),
                },
                "evidence": {
                    "max_age_num_blocks": str(
                        self.consensus_params.evidence.max_age_num_blocks
                    ),
                    "max_age_duration": str(
                        self.consensus_params.evidence.max_age_duration_ns
                    ),
                    "max_bytes": str(
                        self.consensus_params.evidence.max_bytes
                    ),
                },
                "validator": {
                    "pub_key_types": list(
                        self.consensus_params.validator.pub_key_types
                    ),
                },
                "version": {
                    "app_version": str(
                        self.consensus_params.version.app_version
                    ),
                },
            },
            "validators": [
                {
                    "address": v.address.hex().upper(),
                    "pub_key": {
                        "type": v.pub_key.type(),
                        "value": v.pub_key.bytes().hex(),
                    },
                    "power": str(v.power),
                    "name": v.name,
                }
                for v in self.validators
            ],
            "app_hash": self.app_hash.hex().upper(),
        }
        if self.app_state:
            doc["app_state"] = json.loads(self.app_state.decode("utf-8"))
        return json.dumps(doc, indent=2, sort_keys=False)

    @classmethod
    def from_json(cls, data: str) -> "GenesisDoc":
        doc = json.loads(data)
        cp = ConsensusParams()
        p = doc.get("consensus_params") or {}
        if "block" in p:
            cp.block.max_bytes = int(p["block"]["max_bytes"])
            cp.block.max_gas = int(p["block"]["max_gas"])
        if "evidence" in p:
            cp.evidence.max_age_num_blocks = int(
                p["evidence"]["max_age_num_blocks"]
            )
            cp.evidence.max_age_duration_ns = int(
                p["evidence"]["max_age_duration"]
            )
            cp.evidence.max_bytes = int(p["evidence"].get("max_bytes", 0))
        if "validator" in p:
            cp.validator.pub_key_types = list(
                p["validator"]["pub_key_types"]
            )
        if "version" in p:
            cp.version.app_version = int(
                p["version"].get("app_version", 0)
            )
        validators = [
            GenesisValidator(
                pub_key=pubkey_from_type_and_bytes(
                    v["pub_key"]["type"], bytes.fromhex(v["pub_key"]["value"])
                ),
                power=int(v["power"]),
                name=v.get("name", ""),
                address=bytes.fromhex(v.get("address", "")),
            )
            for v in doc.get("validators") or []
        ]
        app_state = b""
        if "app_state" in doc:
            app_state = json.dumps(doc["app_state"]).encode("utf-8")
        g = cls(
            chain_id=doc["chain_id"],
            genesis_time_ns=from_rfc3339(doc["genesis_time"]),
            initial_height=int(doc.get("initial_height", 1)),
            consensus_params=cp,
            validators=validators,
            app_hash=bytes.fromhex(doc.get("app_hash", "")),
            app_state=app_state,
        )
        g.validate_and_complete()
        return g

    def save_as(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @classmethod
    def from_file(cls, path: str) -> "GenesisDoc":
        with open(path) as f:
            return cls.from_json(f.read())
