"""Vote — a signed prevote/precommit from a validator.

Reference: types/vote.go (struct, sign-bytes :93, Verify :147,
ValidateBasic :175), proto field numbers from
proto/tendermint/types/types.pb.go:469-476.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from ..crypto import sigcache
from ..crypto.keys import PubKey
from ..encoding.proto import FieldReader, ProtoWriter
from .block_id import BlockID
from .canonical import PRECOMMIT_TYPE, PREVOTE_TYPE, vote_sign_bytes
from .timestamp import decode_timestamp, encode_timestamp

__all__ = ["Vote", "is_vote_type_valid", "MAX_VOTE_BYTES"]

MAX_VOTE_BYTES = 209  # reference: types/vote.go:33


def is_vote_type_valid(t: int) -> bool:
    return t in (PREVOTE_TYPE, PRECOMMIT_TYPE)


@dataclass
class Vote:
    type: int = PREVOTE_TYPE
    height: int = 0
    round: int = 0
    block_id: BlockID = field(default_factory=BlockID)
    timestamp_ns: int = 0
    validator_address: bytes = b""
    validator_index: int = -1
    signature: bytes = b""

    # fields sign_bytes encodes: assigning any of them (the dataclass
    # __init__ included) drops the encode memo below
    _SB_FIELDS = frozenset(
        {"type", "height", "round", "block_id", "timestamp_ns"}
    )

    def __setattr__(self, name: str, value) -> None:
        if name in self._SB_FIELDS:
            self.__dict__.pop("_sb_memo", None)
        object.__setattr__(self, name, value)

    def sign_bytes(self, chain_id: str) -> bytes:
        """Canonical sign-bytes, memoized per chain_id: one vote is
        encoded up to three times on the hot path (sign/verify-ahead,
        VoteSet.add_vote's cache consult, evidence), always with
        identical inputs. The memo is invalidated by __setattr__ on any
        encoded field, so mutation can never serve stale bytes."""
        memo = self.__dict__.get("_sb_memo")
        if memo is not None and memo[0] == chain_id:
            return memo[1]
        sb = vote_sign_bytes(
            chain_id,
            self.type,
            self.height,
            self.round,
            self.block_id,
            self.timestamp_ns,
        )
        self.__dict__["_sb_memo"] = (chain_id, sb)
        return sb

    def verify(self, chain_id: str, pub_key: PubKey) -> None:
        """Raises ValueError on mismatch/invalid signature
        (reference: types/vote.go:147-157).

        Consults the verified-signature cache after the address check:
        a triple already proven — by the consensus verify-ahead batch
        (consensus/state.py _preverify_votes), a commit verification,
        or an earlier call here — skips the curve math. Successful
        fresh verifies populate the cache, so evidence and LastCommit
        re-checks of this exact vote are free."""
        if pub_key.address() != self.validator_address:
            raise ValueError("invalid validator address")
        sign_bytes = self.sign_bytes(chain_id)
        if sigcache.seen(pub_key.bytes(), sign_bytes, self.signature):
            return
        if not pub_key.verify_signature(sign_bytes, self.signature):
            raise ValueError("invalid signature")
        sigcache.add(pub_key.bytes(), sign_bytes, self.signature)

    def validate_basic(self) -> None:
        if not is_vote_type_valid(self.type):
            raise ValueError("invalid Type")
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        self.block_id.validate_basic()
        if not self.block_id.is_zero() and not self.block_id.is_complete():
            raise ValueError(
                f"blockID must be either empty or complete, got {self.block_id}"
            )
        if len(self.validator_address) != 20:
            raise ValueError("expected ValidatorAddress size to be 20 bytes")
        if self.validator_index < 0:
            raise ValueError("negative ValidatorIndex")
        if len(self.signature) == 0:
            raise ValueError("signature is missing")
        if len(self.signature) > 64:
            raise ValueError("signature is too big")

    def is_nil(self) -> bool:
        return self.block_id.is_zero()

    def copy(self) -> "Vote":
        return replace(self)

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.int(1, self.type)
        w.int(2, self.height)
        w.int(3, self.round)
        w.message(4, self.block_id.to_proto())  # nullable=false
        w.message(5, encode_timestamp(self.timestamp_ns))
        w.bytes(6, self.validator_address)
        w.int(7, self.validator_index)
        w.bytes(8, self.signature)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "Vote":
        r = FieldReader(data)
        bid = r.get(4)
        ts = r.get(5)
        return cls(
            type=r.uint(1),
            height=r.int64(2),
            round=r.int64(3),
            block_id=BlockID.from_proto(bid) if bid is not None else BlockID(),
            timestamp_ns=decode_timestamp(ts) if ts is not None else 0,
            validator_address=r.bytes(6),
            validator_index=r.int64(7),
            signature=r.bytes(8),
        )
