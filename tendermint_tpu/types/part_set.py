"""PartSet — a block split into 64 KiB merkle-proven parts for gossip.

Reference: types/part_set.go (Part :23-90, PartSet :150-380,
NewPartSetFromData :166, AddPart :283), part size
types/params.go:21 (65536).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..crypto import merkle
from ..encoding.proto import FieldReader, ProtoWriter
from ..libs.bits import BitArray
from .block_id import PartSetHeader

__all__ = ["BLOCK_PART_SIZE_BYTES", "Part", "PartSet"]

BLOCK_PART_SIZE_BYTES = 65536  # reference: types/params.go:21


@dataclass
class Part:
    index: int
    bytes: bytes
    proof: merkle.Proof

    def validate_basic(self) -> None:
        if len(self.bytes) > BLOCK_PART_SIZE_BYTES:
            raise ValueError(
                f"too big: {len(self.bytes)} bytes, "
                f"max: {BLOCK_PART_SIZE_BYTES}"
            )

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.uint(1, self.index)
        w.bytes(2, self.bytes)
        w.message(3, self.proof.to_proto_bytes())  # nullable=false
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "Part":
        r = FieldReader(data)
        proof = r.get(3)
        return cls(
            index=r.uint(1),
            bytes=r.bytes(2),
            proof=(
                merkle.Proof.from_proto_bytes(proof)
                if proof is not None
                else merkle.Proof(total=0, index=0, leaf_hash=b"")
            ),
        )


class PartSet:
    """Either built complete from data (proposer side) or filled part by
    part against a trusted header (gossip receiver side)."""

    def __init__(
        self,
        total: int,
        hash_: bytes,
        parts: List[Optional[Part]],
        count: int,
        byte_size: int,
    ) -> None:
        self.total = total
        self.hash = hash_
        self.parts = parts
        self.parts_bit_array = BitArray(total)
        for i, p in enumerate(parts):
            if p is not None:
                self.parts_bit_array.set(i, True)
        self.count = count
        self.byte_size = byte_size

    @classmethod
    def from_data(
        cls, data: bytes, part_size: int = BLOCK_PART_SIZE_BYTES
    ) -> "PartSet":
        """Split + merkle-prove (reference: types/part_set.go:166-194)."""
        total = max(1, (len(data) + part_size - 1) // part_size)
        chunks = [
            data[i * part_size : (i + 1) * part_size] for i in range(total)
        ]
        root, proofs = merkle.proofs_from_byte_slices(chunks)
        parts: List[Optional[Part]] = [
            Part(index=i, bytes=chunks[i], proof=proofs[i])
            for i in range(total)
        ]
        return cls(
            total=total,
            hash_=root,
            parts=parts,
            count=total,
            byte_size=len(data),
        )

    @classmethod
    def from_header(cls, header: PartSetHeader) -> "PartSet":
        return cls(
            total=header.total,
            hash_=header.hash,
            parts=[None] * header.total,
            count=0,
            byte_size=0,
        )

    def header(self) -> PartSetHeader:
        return PartSetHeader(total=self.total, hash=self.hash)

    def has_header(self, header: PartSetHeader) -> bool:
        return self.header() == header

    def get_part(self, index: int) -> Optional[Part]:
        if 0 <= index < self.total:
            return self.parts[index]
        return None

    def add_part(self, part: Part) -> bool:
        """Verify the part's proof against our hash and absorb it.
        Returns False if already present
        (reference: types/part_set.go:283-320)."""
        if part.index >= self.total:
            raise ValueError("error part set unexpected index")
        if self.parts[part.index] is not None:
            return False
        try:
            part.proof.verify(self.hash, part.bytes)
        except ValueError as e:
            raise ValueError(f"error part set invalid proof: {e}") from e
        part.validate_basic()
        self.parts[part.index] = part
        self.parts_bit_array.set(part.index, True)
        self.count += 1
        self.byte_size += len(part.bytes)
        return True

    def is_complete(self) -> bool:
        return self.count == self.total

    def assemble(self) -> bytes:
        """Concatenate all part bytes (reference reads via
        GetReader/MarshalTo)."""
        if not self.is_complete():
            raise ValueError("part set is not complete")
        return b"".join(p.bytes for p in self.parts)  # type: ignore[union-attr]
