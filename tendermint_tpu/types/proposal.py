"""Proposal — a proposed block at (height, round), signed by the proposer.

Reference: types/proposal.go (struct :20-40, ValidateBasic :60-100,
sign-bytes :110), proto fields proto/tendermint/types/types.pb.go:708-714.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto.keys import PubKey
from ..encoding.proto import FieldReader, ProtoWriter
from .block_id import BlockID
from .canonical import PROPOSAL_TYPE, proposal_sign_bytes
from .timestamp import decode_timestamp, encode_timestamp

__all__ = ["Proposal"]


@dataclass
class Proposal:
    type: int = PROPOSAL_TYPE
    height: int = 0
    round: int = 0
    pol_round: int = -1  # -1 when no proof-of-lock
    block_id: BlockID = field(default_factory=BlockID)
    timestamp_ns: int = 0
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return proposal_sign_bytes(
            chain_id,
            self.height,
            self.round,
            self.pol_round,
            self.block_id,
            self.timestamp_ns,
        )

    def verify(self, chain_id: str, pub_key: PubKey) -> bool:
        return pub_key.verify_signature(
            self.sign_bytes(chain_id), self.signature
        )

    def validate_basic(self) -> None:
        if self.type != PROPOSAL_TYPE:
            raise ValueError("invalid Type")
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if self.pol_round < -1:
            raise ValueError("negative POLRound (exception: -1)")
        self.block_id.validate_basic()
        if not self.block_id.is_complete():
            raise ValueError("expected a complete, non-empty BlockID")
        if not self.signature:
            raise ValueError("signature is missing")
        if len(self.signature) > 64:
            raise ValueError("signature is too big")

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.int(1, self.type)
        w.int(2, self.height)
        w.int(3, self.round)
        w.int(4, self.pol_round)
        w.message(5, self.block_id.to_proto())  # nullable=false
        w.message(6, encode_timestamp(self.timestamp_ns))
        w.bytes(7, self.signature)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "Proposal":
        r = FieldReader(data)
        bid = r.get(5)
        ts = r.get(6)
        return cls(
            type=r.uint(1),
            height=r.int64(2),
            round=r.int64(3),
            pol_round=r.int64(4),
            block_id=(
                BlockID.from_proto(bid) if bid is not None else BlockID()
            ),
            timestamp_ns=decode_timestamp(ts) if ts is not None else 0,
            signature=r.bytes(7),
        )
