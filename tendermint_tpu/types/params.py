"""ConsensusParams — consensus-critical limits, hashed into headers.

Reference: types/params.go (structs :37-77, defaults :79-117, Validate
:130-180, HashConsensusParams :185-205, UpdateConsensusParams :213-239),
proto fields proto/tendermint/types/params.pb.go.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import List, Optional

from ..crypto import tmhash
from ..encoding.proto import FieldReader, ProtoWriter, iter_fields

__all__ = [
    "MAX_BLOCK_SIZE_BYTES",
    "MAX_BLOCK_PARTS_COUNT",
    "BlockParams",
    "EvidenceParams",
    "ValidatorParams",
    "VersionParams",
    "ConsensusParams",
]

MAX_BLOCK_SIZE_BYTES = 104857600  # 100 MB (reference: types/params.go:18)
MAX_BLOCK_PARTS_COUNT = MAX_BLOCK_SIZE_BYTES // 65536 + 1

NS_PER_SECOND = 1_000_000_000


@dataclass
class BlockParams:
    max_bytes: int = 22020096  # 21 MB (reference: types/params.go:91)
    max_gas: int = -1

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.int(1, self.max_bytes)
        w.int(2, self.max_gas)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "BlockParams":
        r = FieldReader(data)
        return cls(max_bytes=r.int64(1), max_gas=r.int64(2))


@dataclass
class EvidenceParams:
    max_age_num_blocks: int = 100000
    max_age_duration_ns: int = 48 * 3600 * NS_PER_SECOND
    max_bytes: int = 1048576  # 1 MB

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.int(1, self.max_age_num_blocks)
        # google.protobuf.Duration {seconds=1, nanos=2}
        d = ProtoWriter()
        secs, nanos = divmod(self.max_age_duration_ns, NS_PER_SECOND)
        d.int(1, secs)
        d.int(2, nanos)
        w.message(2, d.finish())  # stdduration, nullable=false
        w.int(3, self.max_bytes)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "EvidenceParams":
        r = FieldReader(data)
        dur = 0
        d = r.get(2)
        if d is not None:
            dr = FieldReader(d)
            dur = dr.int64(1) * NS_PER_SECOND + dr.int64(2)
        return cls(
            max_age_num_blocks=r.int64(1),
            max_age_duration_ns=dur,
            max_bytes=r.int64(3),
        )


@dataclass
class ValidatorParams:
    pub_key_types: List[str] = field(
        default_factory=lambda: ["ed25519"]
    )

    def is_valid_pubkey_type(self, t: str) -> bool:
        return t in self.pub_key_types

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        for t in self.pub_key_types:
            w.string(1, t)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "ValidatorParams":
        types = []
        for f, _wt, v in iter_fields(data):
            if f == 1:
                if not isinstance(v, bytes):
                    # wire-type flip: sanctioned parse error
                    raise ValueError(
                        "ValidatorParams.pub_key_types: expected "
                        "length-delimited"
                    )
                types.append(v.decode("utf-8"))
        return cls(pub_key_types=types)


@dataclass
class VersionParams:
    app_version: int = 0

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.uint(1, self.app_version)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "VersionParams":
        r = FieldReader(data)
        return cls(app_version=r.uint(1))


@dataclass
class ConsensusParams:
    block: BlockParams = field(default_factory=BlockParams)
    evidence: EvidenceParams = field(default_factory=EvidenceParams)
    validator: ValidatorParams = field(default_factory=ValidatorParams)
    version: VersionParams = field(default_factory=VersionParams)

    def validate(self) -> None:
        """reference: types/params.go:130-180."""
        if self.block.max_bytes <= 0:
            raise ValueError("block.MaxBytes must be greater than 0")
        if self.block.max_bytes > MAX_BLOCK_SIZE_BYTES:
            raise ValueError("block.MaxBytes is too big")
        if self.block.max_gas < -1:
            raise ValueError("block.MaxGas must be >= -1")
        if self.evidence.max_age_num_blocks <= 0:
            raise ValueError("evidence.MaxAgeNumBlocks must be > 0")
        if self.evidence.max_age_duration_ns <= 0:
            raise ValueError("evidence.MaxAgeDuration must be > 0")
        if (
            self.evidence.max_bytes > self.block.max_bytes
            or self.evidence.max_bytes < 0
        ):
            raise ValueError("evidence.MaxBytes out of range")
        if not self.validator.pub_key_types:
            raise ValueError("validator.PubKeyTypes must not be empty")

    def hash(self) -> bytes:
        """sha256 of HashedParams{BlockMaxBytes, BlockMaxGas} — the
        Header.ConsensusHash value (reference: types/params.go:185-205,
        proto/tendermint/types/params.pb.go:325-326)."""
        w = ProtoWriter()
        w.int(1, self.block.max_bytes)
        w.int(2, self.block.max_gas)
        return tmhash.sum256(w.finish())

    def update(self, other: Optional["ConsensusParams"]) -> "ConsensusParams":
        """Overlay non-nil sections (reference: types/params.go:213-239).
        `other` here is a full params object; ABCI updates arrive as a
        partial proto handled by update_from_proto."""
        if other is None:
            return replace(self)
        return ConsensusParams(
            block=replace(other.block),
            evidence=replace(other.evidence),
            validator=ValidatorParams(
                pub_key_types=list(other.validator.pub_key_types)
            ),
            version=replace(other.version),
        )

    def update_from_proto(self, data: bytes) -> "ConsensusParams":
        """Apply an ABCI ConsensusParams update (partial message —
        absent sections keep current values)."""
        res = ConsensusParams(
            block=replace(self.block),
            evidence=replace(self.evidence),
            validator=ValidatorParams(
                pub_key_types=list(self.validator.pub_key_types)
            ),
            version=replace(self.version),
        )
        r = FieldReader(data)
        b = r.get(1)
        if b is not None:
            res.block = BlockParams.from_proto(b)
        e = r.get(2)
        if e is not None:
            res.evidence = EvidenceParams.from_proto(e)
        v = r.get(3)
        if v is not None:
            res.validator = ValidatorParams.from_proto(v)
        ver = r.get(4)
        if ver is not None:
            res.version = VersionParams.from_proto(ver)
        return res

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.message(1, self.block.to_proto())
        w.message(2, self.evidence.to_proto())
        w.message(3, self.validator.to_proto())
        w.message(4, self.version.to_proto())
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "ConsensusParams":
        r = FieldReader(data)
        b, e, v, ver = r.get(1), r.get(2), r.get(3), r.get(4)
        return cls(
            block=BlockParams.from_proto(b) if b is not None else BlockParams(),
            evidence=(
                EvidenceParams.from_proto(e)
                if e is not None
                else EvidenceParams()
            ),
            validator=(
                ValidatorParams.from_proto(v)
                if v is not None
                else ValidatorParams()
            ),
            version=(
                VersionParams.from_proto(ver)
                if ver is not None
                else VersionParams()
            ),
        )
