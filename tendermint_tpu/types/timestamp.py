"""Canonical timestamps.

Consensus-critical times are integer nanoseconds since the Unix epoch
(UTC). The reference passes Go time.Time around and marshals it as
google.protobuf.Timestamp {seconds=1, nanos=2} inside sign-bytes
(reference: types/canonical.go:13,70-75, gogoproto stdtime); an integer
avoids Go's monotonic-clock/locale pitfalls entirely while encoding to the
identical wire bytes.
"""

from __future__ import annotations

import time as _time
from datetime import datetime, timezone

from ..encoding.proto import FieldReader, ProtoWriter

__all__ = [
    "encode_timestamp",
    "decode_timestamp",
    "now_ns",
    "to_rfc3339",
    "from_rfc3339",
    "canonical_ns",
]

NS = 1_000_000_000


def now_ns() -> int:
    return _time.time_ns()


def encode_timestamp(ns: int) -> bytes:
    """google.protobuf.Timestamp wire encoding."""
    seconds, nanos = divmod(ns, NS)
    w = ProtoWriter()
    w.int(1, seconds)
    w.int(2, nanos)
    return w.finish()


def decode_timestamp(data: bytes) -> int:
    r = FieldReader(data)
    return r.int64(1) * NS + r.int64(2)


def canonical_ns(ns: int) -> int:
    """Truncate to millisecond precision like libs/time.Canonical
    (reference: libs/time/time.go Canonical: UTC + truncate to ms)."""
    return ns - ns % 1_000_000


def to_rfc3339(ns: int) -> str:
    seconds, nanos = divmod(ns, NS)
    dt = datetime.fromtimestamp(seconds, tz=timezone.utc)
    base = dt.strftime("%Y-%m-%dT%H:%M:%S")
    if nanos:
        frac = f"{nanos:09d}".rstrip("0")
        return f"{base}.{frac}Z"
    return base + "Z"


def from_rfc3339(s: str) -> int:
    if s.endswith("Z"):
        s = s[:-1]
    frac = 0
    if "." in s:
        s, frac_s = s.split(".")
        frac = int(frac_s.ljust(9, "0")[:9])
    dt = datetime.strptime(s, "%Y-%m-%dT%H:%M:%S").replace(
        tzinfo=timezone.utc
    )
    return int(dt.timestamp()) * NS + frac
