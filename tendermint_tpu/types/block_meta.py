"""BlockMeta — header + sizing info stored per height.

Reference: types/block_meta.go, proto fields
proto/tendermint/types/types.pb.go:904-907.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..encoding.proto import FieldReader, ProtoWriter
from .block import Block
from .block_id import BlockID
from .header import Header

__all__ = ["BlockMeta"]


@dataclass
class BlockMeta:
    block_id: BlockID = field(default_factory=BlockID)
    block_size: int = 0
    header: Header = field(default_factory=Header)
    num_txs: int = 0

    @classmethod
    def from_block(cls, block: Block, block_size: int) -> "BlockMeta":
        return cls(
            block_id=BlockID(
                hash=block.hash(),
                part_set_header=block.make_part_set().header(),
            ),
            block_size=block_size,
            header=block.header,
            num_txs=len(block.txs),
        )

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.message(1, self.block_id.to_proto())  # nullable=false
        w.int(2, self.block_size)
        w.message(3, self.header.to_proto())  # nullable=false
        w.int(4, self.num_txs)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "BlockMeta":
        r = FieldReader(data)
        bid = r.get(1)
        h = r.get(3)
        return cls(
            block_id=(
                BlockID.from_proto(bid) if bid is not None else BlockID()
            ),
            block_size=r.int64(2),
            header=Header.from_proto(h) if h is not None else Header(),
            num_txs=r.int64(4),
        )
