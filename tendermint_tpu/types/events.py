"""Event types and payloads published on the event bus.

reference: types/events.go (event value constants :15-47, reserved
composite keys :197-208, payload structs :100-190). Payloads are light
dataclasses; the tag flattening that makes them queryable lives in
tendermint_tpu.eventbus.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

__all__ = [
    "EVENT_TYPE_KEY",
    "TX_HASH_KEY",
    "TX_HEIGHT_KEY",
    "BLOCK_HEIGHT_KEY",
    "EventValue",
    "EventDataNewBlock",
    "EventDataNewBlockHeader",
    "EventDataNewEvidence",
    "EventDataTx",
    "EventDataNewRound",
    "EventDataRoundState",
    "EventDataCompleteProposal",
    "EventDataVote",
    "EventDataValidatorSetUpdates",
    "EventDataBlockSyncStatus",
    "EventDataStateSyncStatus",
]

# Reserved composite keys (reference: types/events.go:197-208)
EVENT_TYPE_KEY = "tm.event"
TX_HASH_KEY = "tx.hash"
TX_HEIGHT_KEY = "tx.height"
BLOCK_HEIGHT_KEY = "block.height"


class EventValue:
    """Event name constants (reference: types/events.go:15-47)."""

    NEW_BLOCK = "NewBlock"
    NEW_BLOCK_HEADER = "NewBlockHeader"
    NEW_EVIDENCE = "NewEvidence"
    TX = "Tx"
    VALIDATOR_SET_UPDATES = "ValidatorSetUpdates"

    COMPLETE_PROPOSAL = "CompleteProposal"
    BLOCK_SYNC_STATUS = "BlockSyncStatus"
    LOCK = "Lock"
    NEW_ROUND = "NewRound"
    NEW_ROUND_STEP = "NewRoundStep"
    POLKA = "Polka"
    RELOCK = "Relock"
    STATE_SYNC_STATUS = "StateSyncStatus"
    TIMEOUT_PROPOSE = "TimeoutPropose"
    TIMEOUT_WAIT = "TimeoutWait"
    UNLOCK = "Unlock"
    VALID_BLOCK = "ValidBlock"
    VOTE = "Vote"


@dataclass(frozen=True)
class EventDataNewBlock:
    block: object  # types.Block
    block_id: object  # types.BlockID
    result_begin_block: object = None  # abci.ResponseBeginBlock
    result_end_block: object = None  # abci.ResponseEndBlock


@dataclass(frozen=True)
class EventDataNewBlockHeader:
    header: object
    num_txs: int = 0
    result_begin_block: object = None
    result_end_block: object = None


@dataclass(frozen=True)
class EventDataNewEvidence:
    evidence: object
    height: int = 0


@dataclass(frozen=True)
class EventDataTx:
    height: int
    tx: bytes
    index: int
    result: object  # abci.ResponseDeliverTx


@dataclass(frozen=True)
class EventDataNewRound:
    height: int
    round: int
    step: str
    proposer_address: bytes = b""
    proposer_index: int = -1


@dataclass(frozen=True)
class EventDataRoundState:
    height: int
    round: int
    step: str


@dataclass(frozen=True)
class EventDataCompleteProposal:
    height: int
    round: int
    step: str
    block_id: object = None


@dataclass(frozen=True)
class EventDataVote:
    vote: object  # types.Vote


@dataclass(frozen=True)
class EventDataValidatorSetUpdates:
    validator_updates: tuple = ()


@dataclass(frozen=True)
class EventDataBlockSyncStatus:
    complete: bool
    height: int


@dataclass(frozen=True)
class EventDataStateSyncStatus:
    complete: bool
    height: int
