"""Validator and ValidatorSet — weighted round-robin proposer selection.

Reference: types/validator.go (Validator, CompareProposerPriority :77,
hash bytes :130), types/validator_set.go (priority increment/rescale
:107-226, GetByAddress :270, Hash :347, change-set application :380-651).

Arithmetic is Python ints (arbitrary precision) clipped to int64 bounds
exactly where the reference uses safeAddClip/safeSubClip, so priority
sequences match Go bit-for-bit even at the clipping edges.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, Iterable, List, Optional, Tuple

from ..crypto import merkle
from ..crypto.keys import PubKey, pubkey_from_proto, pubkey_to_proto
from ..encoding.proto import FieldReader, ProtoWriter, iter_fields

__all__ = [
    "Validator",
    "ValidatorSet",
    "MAX_TOTAL_VOTING_POWER",
    "PRIORITY_WINDOW_SIZE_FACTOR",
]

INT64_MAX = (1 << 63) - 1
INT64_MIN = -(1 << 63)

# reference: types/validator_set.go:25,29
MAX_TOTAL_VOTING_POWER = INT64_MAX // 8
PRIORITY_WINDOW_SIZE_FACTOR = 2


def _clip(v: int) -> int:
    return INT64_MAX if v > INT64_MAX else INT64_MIN if v < INT64_MIN else v


# Process-wide validator-mutation epoch, the ValidatorSet twin of
# commit.py's _MUT_EPOCH: every epoch-pinned set memo (powers_array,
# pubkeys_bytes) is built under the token stored here, and any
# POST-INIT assignment to a Validator field those memos read
# (voting_power, pub_key, address) replaces the token, so the memos
# re-validate lazily on next access. ValidatorSet hands out live
# Validator references, so in-place `v.voting_power = x` without
# _reindex() is a SUPPORTED mutation (the scalar verify paths read it
# live); the epoch hook is what keeps the vectorized tally in lockstep
# with them — the ADVICE-r5 staleness class, closed by invalidation
# instead of rebuild-per-call. proposer_priority writes (every proposer
# rotation) deliberately do not bump: no epoch-pinned memo reads it.
# tmrace: race-ok — single atomic list-slot store of a fresh token;
# concurrent bumps each publish a token unequal to every pinned memo,
# so any interleaving invalidates (the conservative direction)
_VAL_MUT_EPOCH = [object()]

# the Validator fields the epoch-pinned ValidatorSet memos read
_EPOCH_FIELDS = frozenset({"voting_power", "pub_key", "address"})


@dataclass
class Validator:
    pub_key: PubKey
    voting_power: int = 0
    proposer_priority: int = 0
    address: bytes = b""

    def __setattr__(self, name: str, value) -> None:
        # a RE-assignment (the attribute already exists — dataclass
        # __init__ sets each field exactly once on a fresh instance)
        # of a memo-read field invalidates every epoch-pinned set memo
        if name in _EPOCH_FIELDS and name in self.__dict__:
            _VAL_MUT_EPOCH[0] = object()
        object.__setattr__(self, name, value)

    def __post_init__(self) -> None:
        if not self.address and self.pub_key is not None:
            # first derivation on a fresh instance, not a mutation of
            # anything a memo could have read yet: skip the epoch hook
            object.__setattr__(self, "address", self.pub_key.address())

    def copy(self) -> "Validator":
        return replace(self)

    def validate_basic(self) -> None:
        if self.pub_key is None:
            raise ValueError("validator does not have a public key")
        if self.voting_power < 0:
            raise ValueError("validator has negative voting power")
        if len(self.address) != 20:
            raise ValueError("validator address is the wrong size")

    def hash_bytes(self) -> bytes:
        """SimpleValidator proto (pubkey + power, no priority/address) —
        the validator-set hash leaf (reference: types/validator.go:130-145,
        proto/tendermint/types/validator.pb.go:156-157)."""
        w = ProtoWriter()
        w.message(1, pubkey_to_proto(self.pub_key))
        w.int(2, self.voting_power)
        return w.finish()

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.bytes(1, self.address)
        w.message(2, pubkey_to_proto(self.pub_key))  # nullable=false
        w.int(3, self.voting_power)
        w.int(4, self.proposer_priority)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "Validator":
        r = FieldReader(data)
        pk = r.get(2)
        if pk is None:
            raise ValueError("validator proto missing pub_key")
        return cls(
            pub_key=pubkey_from_proto(pk),
            voting_power=r.int64(3),
            proposer_priority=r.int64(4),
            address=r.bytes(1),
        )


def _cmp_most_priority(a: Validator, b: Validator) -> Validator:
    """Higher priority wins; ties break toward the lower address
    (reference: types/validator.go:77-97)."""
    if a.proposer_priority > b.proposer_priority:
        return a
    if a.proposer_priority < b.proposer_priority:
        return b
    if a.address < b.address:
        return a
    if a.address > b.address:
        return b
    raise ValueError("cannot compare identical validators")


class ValidatorSet:
    """Validators sorted by voting power desc, then address asc.

    reference: types/validator_set.go:50-80. Maintains an address index
    for O(1) GetByAddress (the reference does binary search; same
    observable behavior).
    """

    def __init__(self, validators: Optional[Iterable[Validator]] = None):
        self.validators: List[Validator] = []
        self.proposer: Optional[Validator] = None
        self._total_voting_power = 0
        self._addr_index: Dict[bytes, int] = {}
        self._hash: Optional[bytes] = None
        self._proto_memo: Optional[tuple] = None
        self._fp_token: Optional[object] = None
        self._pkb_memo: Optional[tuple] = None
        self._powers_memo: Optional[tuple] = None
        valz = [v.copy() for v in validators] if validators else []
        self._update_with_change_set(valz, allow_deletes=False)
        if valz:
            self.increment_proposer_priority(1)

    # -- basic accessors --

    def is_nil_or_empty(self) -> bool:
        return not self.validators

    def size(self) -> int:
        return len(self.validators)

    def __len__(self) -> int:
        return len(self.validators)

    def has_address(self, address: bytes) -> bool:
        return address in self._addr_index

    def get_by_address(
        self, address: bytes
    ) -> Tuple[int, Optional[Validator]]:
        """(index, validator) or (-1, None)
        (reference: types/validator_set.go:270)."""
        i = self._addr_index.get(address)
        if i is None:
            return -1, None
        return i, self.validators[i].copy()

    def get_by_index(
        self, index: int
    ) -> Tuple[bytes, Optional[Validator]]:
        if index < 0 or index >= len(self.validators):
            return b"", None
        v = self.validators[index]
        return v.address, v.copy()

    def powers_array(self):
        """Voting powers as a read-only np.int64 array aligned with
        self.validators, memoized under the process-wide validator-
        mutation epoch (_VAL_MUT_EPOCH). This class hands out live
        Validator references, so in-place power mutation without
        _reindex() is supported and the scalar verify paths see it
        immediately; a plain memo here would split the vectorized
        VerifyCommit tally from them (the to_proto ADVICE-r5 staleness
        class). Validator.__setattr__ replaces the epoch token on any
        post-init voting_power/pub_key/address write, so the memo
        re-validates with one `is` comparison on the warm path — the
        10k-attribute fromiter walk this replaces was the single
        largest slice of the warm verify_commit scan (PERF.md
        warm-path breakdown) — and membership changes clear it through
        _reindex() like every other set memo."""
        epoch = _VAL_MUT_EPOCH[0]
        memo = self._powers_memo
        if memo is not None and memo[0] is epoch:
            return memo[1]
        import numpy as np

        arr = np.fromiter(
            (v.voting_power for v in self.validators),
            dtype=np.int64,
            count=len(self.validators),
        )
        arr.setflags(write=False)
        self._powers_memo = (epoch, arr)
        return arr

    def fingerprint_token(self):
        """Membership-identity token for the commit-level verification
        memo (types/validation.py): a unique object, replaced by
        _reindex() — the single choke point every membership mutation
        path runs through — and never shared with copies (copy() mints
        its own), so a sigcache commit key holding it can only ever hit
        for this exact set composition. In-place voting_power mutation
        does NOT move the token; the commit-memo key covers powers
        separately with the powers_array() bytes, which the epoch hook
        keeps live under in-place mutation (the ADVICE-r5 staleness
        class). An in-place pub_key swap that bypasses
        update_with_change_set is not covered — the same unsupported
        mutation that already leaves hash() and _addr_index stale."""
        if self._fp_token is None:
            self._fp_token = object()
        return self._fp_token

    def pubkeys_bytes(self) -> List[bytes]:
        """Raw pubkey encodings aligned with self.validators, memoized
        under the validator-mutation epoch and treated read-only by
        callers — the warm VerifyCommit scan builds 10k cache keys from
        these and the per-call `v.pub_key.bytes()` walk was a dominant
        slice of its Python cost (PERF.md warm-path breakdown).
        Invalidated by _reindex() like hash(), and additionally by the
        epoch hook on an in-place pub_key re-assignment — a mutation
        that still leaves _addr_index and hash() stale (unsupported as
        before), but can no longer serve this memo stale bytes."""
        epoch = _VAL_MUT_EPOCH[0]
        memo = self._pkb_memo
        if memo is not None and memo[0] is epoch:
            return memo[1]
        pkb = [v.pub_key.bytes() for v in self.validators]
        self._pkb_memo = (epoch, pkb)
        return pkb

    def total_voting_power(self) -> int:
        if self._total_voting_power == 0:
            self._update_total_voting_power()
        return self._total_voting_power

    def copy(self) -> "ValidatorSet":
        new = ValidatorSet.__new__(ValidatorSet)
        new.validators = [v.copy() for v in self.validators]
        new.proposer = self.proposer.copy() if self.proposer else None
        new._total_voting_power = self._total_voting_power
        new._addr_index = dict(self._addr_index)
        new._hash = self._hash  # same membership -> same merkle root
        new._proto_memo = None
        new._fp_token = None  # copies diverge independently: own token
        new._pkb_memo = None
        new._powers_memo = None
        return new

    def _reindex(self) -> None:
        self._addr_index = {
            v.address: i for i, v in enumerate(self.validators)
        }
        self._hash = None  # membership changed; recompute lazily
        self._proto_memo = None
        self._fp_token = None
        self._pkb_memo = None
        self._powers_memo = None

    def _update_total_voting_power(self) -> None:
        total = 0
        for v in self.validators:
            total += v.voting_power
            if total > MAX_TOTAL_VOTING_POWER:
                raise OverflowError(
                    f"total voting power exceeds max {MAX_TOTAL_VOTING_POWER}"
                )
        self._total_voting_power = total

    # -- proposer selection (reference: types/validator_set.go:107-226) --

    def get_proposer(self) -> Validator:
        if not self.validators:
            raise ValueError("empty validator set")
        if self.proposer is None:
            self.proposer = self._find_proposer()
        return self.proposer.copy()

    def _find_proposer(self) -> Validator:
        result = None
        for v in self.validators:
            result = v if result is None else _cmp_most_priority(result, v)
        return result

    def increment_proposer_priority(self, times: int) -> None:
        if not self.validators:
            raise ValueError("empty validator set")
        if times <= 0:
            raise ValueError("times must be positive")
        diff_max = PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power()
        self.rescale_priorities(diff_max)
        self._shift_by_avg_proposer_priority()
        proposer = None
        for _ in range(times):
            proposer = self._increment_proposer_priority()
        self.proposer = proposer

    def copy_increment_proposer_priority(self, times: int) -> "ValidatorSet":
        c = self.copy()
        c.increment_proposer_priority(times)
        return c

    def _increment_proposer_priority(self) -> Validator:
        for v in self.validators:
            v.proposer_priority = _clip(
                v.proposer_priority + v.voting_power
            )
        mostest = self._find_proposer()
        mostest.proposer_priority = _clip(
            mostest.proposer_priority - self.total_voting_power()
        )
        return mostest

    def rescale_priorities(self, diff_max: int) -> None:
        if not self.validators:
            raise ValueError("empty validator set")
        if diff_max <= 0:
            return
        prios = [v.proposer_priority for v in self.validators]
        diff = max(prios) - min(prios)
        if diff < 0:
            diff = -diff
        ratio = (diff + diff_max - 1) // diff_max
        if diff > diff_max:
            for v in self.validators:
                # Go integer division truncates toward zero
                p = v.proposer_priority
                v.proposer_priority = (
                    -((-p) // ratio) if p < 0 else p // ratio
                )

    def _compute_avg_proposer_priority(self) -> int:
        n = len(self.validators)
        s = sum(v.proposer_priority for v in self.validators)
        # Go big.Int.Div uses Euclidean... actually Div is floored for
        # positive divisor: rounds toward negative infinity. Python //
        # matches for positive n.
        return s // n

    def _shift_by_avg_proposer_priority(self) -> None:
        avg = self._compute_avg_proposer_priority()
        for v in self.validators:
            v.proposer_priority = _clip(v.proposer_priority - avg)

    # -- hashing --

    def hash(self) -> bytes:
        """Merkle root of SimpleValidator leaves
        (reference: types/validator_set.go:347-353). Memoized: the
        root covers only (pub_key, voting_power) in order — NOT
        proposer priorities — so it survives proposer rotation and is
        invalidated by _reindex(), which every membership/power
        mutation path calls. Light sync and consensus re-hash the
        same 150+ validator set several times per header otherwise."""
        if self._hash is None:
            self._hash = merkle.hash_from_byte_slices(
                [v.hash_bytes() for v in self.validators]
            )
        return self._hash

    # -- change-set application (reference: validator_set.go:380-651) --

    def update_with_change_set(self, changes: List[Validator]) -> None:
        self._update_with_change_set(
            [c.copy() for c in changes], allow_deletes=True
        )

    def _update_with_change_set(
        self, changes: List[Validator], allow_deletes: bool
    ) -> None:
        if not changes:
            return
        updates, deletes = self._process_changes(changes)
        if not allow_deletes and deletes:
            raise ValueError(
                "cannot process validators with voting power 0"
            )
        num_new = sum(
            1 for u in updates if not self.has_address(u.address)
        )
        if num_new == 0 and len(self.validators) == len(deletes):
            raise ValueError(
                "applying the validator changes would result in empty set"
            )
        removed_power = self._verify_removals(deletes)
        tvp_after = self._verify_updates(updates, removed_power)
        # priorities for new validators: -1.125 * updated total power
        for u in updates:
            _, existing = self.get_by_address(u.address)
            if existing is None:
                u.proposer_priority = -(tvp_after + (tvp_after >> 3))
            else:
                u.proposer_priority = existing.proposer_priority
        self._apply_updates(updates)
        self._apply_removals(deletes)
        self._total_voting_power = 0
        self._update_total_voting_power()
        self.rescale_priorities(
            PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power()
        )
        self._shift_by_avg_proposer_priority()
        # sort by voting power desc, address asc
        self.validators.sort(key=lambda v: (-v.voting_power, v.address))
        self._reindex()

    @staticmethod
    def _process_changes(
        changes: List[Validator],
    ) -> Tuple[List[Validator], List[Validator]]:
        by_addr = sorted(changes, key=lambda v: v.address)
        updates: List[Validator] = []
        removals: List[Validator] = []
        prev_addr = None
        for c in by_addr:
            if c.address == prev_addr:
                raise ValueError(f"duplicate entry {c.address.hex()}")
            if c.voting_power < 0:
                raise ValueError("voting power can't be negative")
            if c.voting_power > MAX_TOTAL_VOTING_POWER:
                raise ValueError(
                    f"voting power can't be higher than {MAX_TOTAL_VOTING_POWER}"
                )
            (removals if c.voting_power == 0 else updates).append(c)
            prev_addr = c.address
        return updates, removals

    def _verify_removals(self, deletes: List[Validator]) -> int:
        removed = 0
        for d in deletes:
            _, val = self.get_by_address(d.address)
            if val is None:
                raise ValueError(
                    f"failed to find validator {d.address.hex()} to remove"
                )
            removed += val.voting_power
        if len(deletes) > len(self.validators):
            raise ValueError("more deletes than validators")
        return removed

    def _verify_updates(
        self, updates: List[Validator], removed_power: int
    ) -> int:
        def delta(u: Validator) -> int:
            _, val = self.get_by_address(u.address)
            return (
                u.voting_power - val.voting_power
                if val is not None
                else u.voting_power
            )

        tvp_after_removals = self.total_voting_power() - removed_power
        for u in sorted(updates, key=delta):
            tvp_after_removals += delta(u)
            if tvp_after_removals > MAX_TOTAL_VOTING_POWER:
                raise OverflowError(
                    "total voting power of resulting valset exceeds max"
                )
        return tvp_after_removals + removed_power

    def _apply_updates(self, updates: List[Validator]) -> None:
        existing = sorted(self.validators, key=lambda v: v.address)
        updates = sorted(updates, key=lambda v: v.address)
        merged: List[Validator] = []
        i = j = 0
        while i < len(existing) and j < len(updates):
            if existing[i].address < updates[j].address:
                merged.append(existing[i])
                i += 1
            else:
                merged.append(updates[j])
                if existing[i].address == updates[j].address:
                    i += 1
                j += 1
        merged.extend(existing[i:])
        merged.extend(updates[j:])
        self.validators = merged
        self._reindex()

    def _apply_removals(self, deletes: List[Validator]) -> None:
        if not deletes:
            return
        dead = {d.address for d in deletes}
        self.validators = [
            v for v in self.validators if v.address not in dead
        ]
        self._reindex()

    # -- proto --

    def to_proto(self) -> bytes:
        """Memoized: the light client saves one LightBlock per header
        and every one of them embeds the SAME 150-validator set, so
        without the memo the pure-Python proto writer re-serializes
        ~150 pubkeys per header (more than half of measured sync time).
        Unlike hash(), the wire form covers proposer priorities, which
        mutate in place outside _reindex (increment_proposer_priority)
        — so the memo is validated against a cheap fingerprint of
        the mutable inputs on every call instead of trusting an
        invalidation hook. The fingerprint covers EVERY field the wire
        form reads per validator — priority, voting_power, pub_key
        identity, address — because this class hands out live
        Validator references (validators list, get_by_address): an
        embedder mutating a validator's power or key in place must get
        fresh bytes, not the memo (ADVICE r5)."""
        key = (
            tuple(
                (
                    v.address,
                    v.pub_key.bytes() if v.pub_key is not None else b"",
                    v.voting_power,
                    v.proposer_priority,
                )
                for v in self.validators
            ),
            # the proposer's full mutable record, not just its address:
            # copy()/from_proto() can leave self.proposer detached from
            # its list entry, so its fields can change independently
            (
                (
                    self.proposer.address,
                    (
                        self.proposer.pub_key.bytes()
                        if self.proposer.pub_key is not None
                        else b""
                    ),
                    self.proposer.voting_power,
                    self.proposer.proposer_priority,
                )
                if self.proposer is not None
                else None
            ),
        )
        memo = getattr(self, "_proto_memo", None)
        if memo is not None and memo[0] == key:
            return memo[1]
        w = ProtoWriter()
        for v in self.validators:
            w.message(1, v.to_proto())
        if self.proposer is not None:
            w.message(2, self.proposer.to_proto())
        w.int(3, self.total_voting_power())
        out = w.finish()
        self._proto_memo = (key, out)
        return out

    @classmethod
    def from_proto(cls, data: bytes) -> "ValidatorSet":
        # tmcheck: unparsed=3 — total_voting_power is recomputed from
        # the validators (reference ValidatorSetFromProto does the
        # same); trusting the wire value would let a peer lie about it
        vals: List[Validator] = []
        proposer = None
        for f, _wt, v in iter_fields(data):
            if f == 1:
                vals.append(Validator.from_proto(v))
            elif f == 2:
                proposer = Validator.from_proto(v)
        new = cls.__new__(cls)
        new.validators = vals
        new.proposer = proposer
        new._total_voting_power = 0
        new._reindex()  # one invalidation point: index + hash memo
        return new

    def validate_basic(self) -> None:
        if self.is_nil_or_empty():
            raise ValueError("validator set is nil or empty")
        for i, v in enumerate(self.validators):
            try:
                v.validate_basic()
            except ValueError as e:
                raise ValueError(f"invalid validator #{i}: {e}") from e
        if self.proposer is None:
            raise ValueError("proposer failed validate basic: nil")
        self.proposer.validate_basic()

    def __repr__(self) -> str:
        return (
            f"ValidatorSet(n={len(self.validators)}, "
            f"power={self.total_voting_power()})"
        )
