"""Block header and its field-merkle hash.

Reference: types/block.go:330-520 (Header struct, ValidateBasic :371,
Hash :448 — a merkle tree whose leaves are the proto encodings of each
field in declaration order), encoding helper cdcEncode
(types/encoding_helper.go: primitives wrapped in gogotypes *Value
single-field messages).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import merkle, tmhash
from ..encoding.proto import FieldReader, ProtoWriter
from .block_id import BlockID
from .timestamp import decode_timestamp, encode_timestamp

__all__ = ["Consensus", "Header", "BLOCK_PROTOCOL"]

BLOCK_PROTOCOL = 11  # reference: version/version.go:27


@dataclass(frozen=True)
class Consensus:
    """Block/app protocol versions (reference:
    proto/tendermint/version/types.pb.go:30-31)."""

    block: int = BLOCK_PROTOCOL
    app: int = 0

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.uint(1, self.block)
        w.uint(2, self.app)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "Consensus":
        r = FieldReader(data)
        return cls(block=r.uint(1), app=r.uint(2))


def _cdc_bytes(value: bytes) -> bytes:
    """gogotypes.BytesValue{Value: v}.Marshal() — nil for empty
    (reference: types/encoding_helper.go)."""
    if not value:
        return b""
    w = ProtoWriter()
    w.bytes(1, value)
    return w.finish()


def _cdc_string(value: str) -> bytes:
    if not value:
        return b""
    w = ProtoWriter()
    w.string(1, value)
    return w.finish()


def _cdc_int64(value: int) -> bytes:
    if not value:
        return b""
    w = ProtoWriter()
    w.int(1, value)
    return w.finish()


@dataclass
class Header:
    version: Consensus = field(default_factory=Consensus)
    chain_id: str = ""
    height: int = 0
    time_ns: int = 0
    last_block_id: BlockID = field(default_factory=BlockID)
    last_commit_hash: bytes = b""
    data_hash: bytes = b""
    validators_hash: bytes = b""
    next_validators_hash: bytes = b""
    consensus_hash: bytes = b""
    app_hash: bytes = b""
    last_results_hash: bytes = b""
    evidence_hash: bytes = b""
    proposer_address: bytes = b""

    # every field feeds the field-merkle, so assigning ANY attribute
    # (the dataclass __init__ included) drops the hash memo below —
    # same invalidation discipline as Vote._SB_FIELDS
    def __setattr__(self, name: str, value) -> None:
        self.__dict__.pop("_hash_memo", None)
        object.__setattr__(self, name, value)

    def hash(self) -> bytes:
        """Merkle tree over the 14 fields in declaration order
        (reference: types/block.go:448-485). Empty if ValidatorsHash is
        missing (header not yet populated).

        Memoized: one header is hashed repeatedly on the hot path
        (proposal/part-set identity, prevote targets, validate_block,
        commit finalization, evidence time lookups), always with
        identical fields. __setattr__ invalidation means mutation can
        never serve a stale hash."""
        if not self.validators_hash:
            return b""
        memo = self.__dict__.get("_hash_memo")
        if memo is not None:
            return memo
        leaves = [
            self.version.to_proto(),
            _cdc_string(self.chain_id),
            _cdc_int64(self.height),
            encode_timestamp(self.time_ns),
            self.last_block_id.to_proto(),
            _cdc_bytes(self.last_commit_hash),
            _cdc_bytes(self.data_hash),
            _cdc_bytes(self.validators_hash),
            _cdc_bytes(self.next_validators_hash),
            _cdc_bytes(self.consensus_hash),
            _cdc_bytes(self.app_hash),
            _cdc_bytes(self.last_results_hash),
            _cdc_bytes(self.evidence_hash),
            _cdc_bytes(self.proposer_address),
        ]
        h = merkle.hash_from_byte_slices(leaves)
        self.__dict__["_hash_memo"] = h
        return h

    def validate_basic(self) -> None:
        if len(self.chain_id) > 50:
            raise ValueError("chainID is too long")
        if self.height < 0:
            raise ValueError("negative Height")
        if self.height == 0:
            raise ValueError("zero Height")
        self.last_block_id.validate_basic()
        for name in (
            "last_commit_hash",
            "data_hash",
            "evidence_hash",
            "validators_hash",
            "next_validators_hash",
            "consensus_hash",
            "last_results_hash",
        ):
            h = getattr(self, name)
            if h and len(h) != tmhash.SIZE:
                raise ValueError(f"wrong {name}: expected size {tmhash.SIZE}")
        if len(self.proposer_address) != 20:
            raise ValueError("invalid ProposerAddress length")

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.message(1, self.version.to_proto())  # nullable=false
        w.string(2, self.chain_id)
        w.int(3, self.height)
        w.message(4, encode_timestamp(self.time_ns))
        w.message(5, self.last_block_id.to_proto())
        w.bytes(6, self.last_commit_hash)
        w.bytes(7, self.data_hash)
        w.bytes(8, self.validators_hash)
        w.bytes(9, self.next_validators_hash)
        w.bytes(10, self.consensus_hash)
        w.bytes(11, self.app_hash)
        w.bytes(12, self.last_results_hash)
        w.bytes(13, self.evidence_hash)
        w.bytes(14, self.proposer_address)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "Header":
        r = FieldReader(data)
        ver = r.get(1)
        ts = r.get(4)
        bid = r.get(5)
        return cls(
            version=Consensus.from_proto(ver) if ver is not None else Consensus(0, 0),
            chain_id=r.string(2),
            height=r.int64(3),
            time_ns=decode_timestamp(ts) if ts is not None else 0,
            last_block_id=(
                BlockID.from_proto(bid) if bid is not None else BlockID()
            ),
            last_commit_hash=r.bytes(6),
            data_hash=r.bytes(7),
            validators_hash=r.bytes(8),
            next_validators_hash=r.bytes(9),
            consensus_hash=r.bytes(10),
            app_hash=r.bytes(11),
            last_results_hash=r.bytes(12),
            evidence_hash=r.bytes(13),
            proposer_address=r.bytes(14),
        )
