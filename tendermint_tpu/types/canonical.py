"""Canonical sign-bytes for votes and proposals.

The byte strings validators sign. Must match the reference exactly:
CanonicalVote / CanonicalProposal (reference: types/canonical.go:42-66,
proto/tendermint/types/canonical.proto) marshalled with a varint length
prefix (protoio.MarshalDelimited — reference: types/vote.go:93-101,
types/proposal.go:110-118).

Height and round are sfixed64 here (canonicalization requires fixed-size
encoding, per the comment in canonical.proto) while the non-canonical
Vote/Proposal messages use varints.
"""

from __future__ import annotations

from ..encoding.proto import ProtoWriter, encode_varint, length_prefixed
from .block_id import BlockID
from .timestamp import encode_timestamp

__all__ = [
    "PREVOTE_TYPE",
    "PRECOMMIT_TYPE",
    "PROPOSAL_TYPE",
    "VoteSignTemplate",
    "canonical_block_id",
    "canonical_vote_bytes",
    "vote_sign_bytes",
    "proposal_sign_bytes",
]

# SignedMsgType enum (proto/tendermint/types/types.pb.go SignedMsgType:
# prevote=1, precommit=2, proposal=32)
PREVOTE_TYPE = 1
PRECOMMIT_TYPE = 2
PROPOSAL_TYPE = 32


def canonical_block_id(block_id: BlockID) -> bytes | None:
    """CanonicalBlockID body, or None for a zero BlockID (nil votes carry
    no block_id field at all — reference: types/canonical.go:18-34)."""
    if block_id.is_zero():
        return None
    w = ProtoWriter()
    w.bytes(1, block_id.hash)
    # CanonicalPartSetHeader, gogoproto nullable=false → always written
    psh = ProtoWriter()
    psh.uint(1, block_id.part_set_header.total)
    psh.bytes(2, block_id.part_set_header.hash)
    w.message(2, psh.finish())
    return w.finish()


def canonical_vote_bytes(
    msg_type: int,
    height: int,
    round_: int,
    block_id: BlockID,
    timestamp_ns: int,
    chain_id: str,
) -> bytes:
    """CanonicalVote message body (no length prefix)."""
    w = ProtoWriter()
    w.int(1, msg_type)
    w.sfixed64(2, height)
    w.sfixed64(3, round_)
    w.message(4, canonical_block_id(block_id))
    # Timestamp, nullable=false → always written, even epoch zero
    w.message(5, encode_timestamp(timestamp_ns))
    w.string(6, chain_id)
    return w.finish()


def vote_sign_bytes(
    chain_id: str,
    msg_type: int,
    height: int,
    round_: int,
    block_id: BlockID,
    timestamp_ns: int,
) -> bytes:
    """The exact bytes a validator signs for a vote
    (reference: types/vote.go:93)."""
    return length_prefixed(
        canonical_vote_bytes(
            msg_type, height, round_, block_id, timestamp_ns, chain_id
        )
    )


class VoteSignTemplate:
    """Splice fast path for per-commit sign-bytes assembly.

    Within one commit every canonical vote shares type/height/round/
    block_id/chain_id; only the timestamp differs per signature. The
    full ProtoWriter path costs ~14 us per vote — 140 ms for a
    10k-validator commit, far outside the <5 ms VerifyCommit target —
    so the fixed fields are encoded once (prefix = fields 1-4,
    suffix = field 6) and per signature only the Timestamp submessage
    (field 5, always written: gogoproto nullable=false) is re-encoded
    and spliced between them. Output is byte-identical to
    vote_sign_bytes() (asserted by tests/test_encoding.py).
    Reference seam: types/validation.go:152 marshals the same bytes
    per signature."""

    __slots__ = ("_prefix", "_suffix")

    _TS_TAG = bytes([(5 << 3) | 2])  # field 5, wire type 2

    def __init__(
        self,
        chain_id: str,
        msg_type: int,
        height: int,
        round_: int,
        block_id: BlockID,
    ) -> None:
        w = ProtoWriter()
        w.int(1, msg_type)
        w.sfixed64(2, height)
        w.sfixed64(3, round_)
        w.message(4, canonical_block_id(block_id))
        self._prefix = w.finish()
        w = ProtoWriter()
        w.string(6, chain_id)
        self._suffix = w.finish()

    def sign_bytes(self, timestamp_ns: int) -> bytes:
        ts = encode_timestamp(timestamp_ns)
        body = b"".join(
            (
                self._prefix,
                self._TS_TAG,
                encode_varint(len(ts)),
                ts,
                self._suffix,
            )
        )
        return encode_varint(len(body)) + body

    def sign_bytes_batch(self, timestamps_ns) -> list:
        """sign_bytes for a sequence of timestamps in one tight loop —
        the Timestamp submessage is varint-encoded inline (no
        ProtoWriter construction per call). Routed through the native
        assembler (native/signbytes.c, ~100x this loop) when the
        toolchain allows; byte-identical by contract and by
        differential test (tests/test_encoding.py). Used by the
        VerifyCommit batch path where sign-bytes assembly is the
        dominant host cost."""
        # materialize up front: the native path needs len() and a
        # second pass for the int64 range check — a half-consumed
        # generator must not silently shrink the fallback loop's input
        timestamps_ns = list(timestamps_ns)
        native_rows = self._sign_bytes_batch_native(timestamps_ns)
        if native_rows is not None:
            return native_rows
        prefix, suffix, ts_tag = self._prefix, self._suffix, self._TS_TAG
        enc, join = encode_varint, b"".join
        out = []
        append = out.append
        for ns in timestamps_ns:
            seconds, nanos = divmod(ns, 1_000_000_000)
            # google.protobuf.Timestamp {1: int64 seconds, 2: int32 nanos},
            # zero fields omitted (proto3 defaults)
            ts = b""
            if seconds:
                ts = b"\x08" + enc(seconds)
            if nanos:
                ts += b"\x10" + enc(nanos)
            body = join((prefix, ts_tag, enc(len(ts)), ts, suffix))
            append(enc(len(body)) + body)
        return out

    def _sign_bytes_batch_native(self, timestamps_ns):
        """The C assembler path, or None to use the Python loop
        (toolchain unavailable, or a timestamp outside int64 — the
        Python loop handles arbitrary ints)."""
        import ctypes

        from ..native import signbytes_lib

        lib = signbytes_lib()
        if lib is None:
            return None
        n = len(timestamps_ns)
        if n == 0:
            return []
        vals = list(timestamps_ns)
        lo, hi = -(1 << 63), (1 << 63) - 1
        # explicit range check: ctypes c_int64 assignment silently
        # wraps out-of-range Python ints instead of raising
        if not all(lo <= v <= hi for v in vals):
            return None
        ts = (ctypes.c_int64 * n)(*vals)
        cap = n * (len(self._prefix) + len(self._suffix) + 24)
        out = ctypes.create_string_buffer(cap)
        lens = (ctypes.c_int32 * n)()
        total = lib.tm_vote_sign_bytes_batch(
            self._prefix,
            len(self._prefix),
            self._suffix,
            len(self._suffix),
            self._TS_TAG[0],
            ts,
            n,
            out,
            cap,
            lens,
        )
        if total < 0:  # pragma: no cover - cap is a proven bound
            return None
        rows = []
        off = 0
        raw = out.raw
        for i in range(n):
            end = off + lens[i]
            rows.append(raw[off:end])
            off = end
        return rows


def proposal_sign_bytes(
    chain_id: str,
    height: int,
    round_: int,
    pol_round: int,
    block_id: BlockID,
    timestamp_ns: int,
) -> bytes:
    """CanonicalProposal sign-bytes (reference: types/proposal.go:110,
    types/canonical.go:42-53). pol_round is varint int64; -1 means none."""
    w = ProtoWriter()
    w.int(1, PROPOSAL_TYPE)
    w.sfixed64(2, height)
    w.sfixed64(3, round_)
    w.int(4, pol_round)
    w.message(5, canonical_block_id(block_id))
    w.message(6, encode_timestamp(timestamp_ns))
    w.string(7, chain_id)
    return length_prefixed(w.finish())
