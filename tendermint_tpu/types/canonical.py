"""Canonical sign-bytes for votes and proposals.

The byte strings validators sign. Must match the reference exactly:
CanonicalVote / CanonicalProposal (reference: types/canonical.go:42-66,
proto/tendermint/types/canonical.proto) marshalled with a varint length
prefix (protoio.MarshalDelimited — reference: types/vote.go:93-101,
types/proposal.go:110-118).

Height and round are sfixed64 here (canonicalization requires fixed-size
encoding, per the comment in canonical.proto) while the non-canonical
Vote/Proposal messages use varints.
"""

from __future__ import annotations

from ..encoding.proto import ProtoWriter, length_prefixed
from .block_id import BlockID
from .timestamp import encode_timestamp

__all__ = [
    "PREVOTE_TYPE",
    "PRECOMMIT_TYPE",
    "PROPOSAL_TYPE",
    "canonical_block_id",
    "canonical_vote_bytes",
    "vote_sign_bytes",
    "proposal_sign_bytes",
]

# SignedMsgType enum (proto/tendermint/types/types.pb.go SignedMsgType:
# prevote=1, precommit=2, proposal=32)
PREVOTE_TYPE = 1
PRECOMMIT_TYPE = 2
PROPOSAL_TYPE = 32


def canonical_block_id(block_id: BlockID) -> bytes | None:
    """CanonicalBlockID body, or None for a zero BlockID (nil votes carry
    no block_id field at all — reference: types/canonical.go:18-34)."""
    if block_id.is_zero():
        return None
    w = ProtoWriter()
    w.bytes(1, block_id.hash)
    # CanonicalPartSetHeader, gogoproto nullable=false → always written
    psh = ProtoWriter()
    psh.uint(1, block_id.part_set_header.total)
    psh.bytes(2, block_id.part_set_header.hash)
    w.message(2, psh.finish())
    return w.finish()


def canonical_vote_bytes(
    msg_type: int,
    height: int,
    round_: int,
    block_id: BlockID,
    timestamp_ns: int,
    chain_id: str,
) -> bytes:
    """CanonicalVote message body (no length prefix)."""
    w = ProtoWriter()
    w.int(1, msg_type)
    w.sfixed64(2, height)
    w.sfixed64(3, round_)
    w.message(4, canonical_block_id(block_id))
    # Timestamp, nullable=false → always written, even epoch zero
    w.message(5, encode_timestamp(timestamp_ns))
    w.string(6, chain_id)
    return w.finish()


def vote_sign_bytes(
    chain_id: str,
    msg_type: int,
    height: int,
    round_: int,
    block_id: BlockID,
    timestamp_ns: int,
) -> bytes:
    """The exact bytes a validator signs for a vote
    (reference: types/vote.go:93)."""
    return length_prefixed(
        canonical_vote_bytes(
            msg_type, height, round_, block_id, timestamp_ns, chain_id
        )
    )


def proposal_sign_bytes(
    chain_id: str,
    height: int,
    round_: int,
    pol_round: int,
    block_id: BlockID,
    timestamp_ns: int,
) -> bytes:
    """CanonicalProposal sign-bytes (reference: types/proposal.go:110,
    types/canonical.go:42-53). pol_round is varint int64; -1 means none."""
    w = ProtoWriter()
    w.int(1, PROPOSAL_TYPE)
    w.sfixed64(2, height)
    w.sfixed64(3, round_)
    w.int(4, pol_round)
    w.message(5, canonical_block_id(block_id))
    w.message(6, encode_timestamp(timestamp_ns))
    w.string(7, chain_id)
    return length_prefixed(w.finish())
