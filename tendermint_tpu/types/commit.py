"""Commit and CommitSig — the 2/3-majority precommit record in a block.

Reference: types/block.go:560-930 (CommitSig :560-700, Commit :760-930),
proto field numbers proto/tendermint/types/types.pb.go:571-574,640-643.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..crypto import merkle
from ..encoding.proto import FieldReader, ProtoWriter, iter_fields
from ..libs.bits import BitArray
from .block_id import BlockID
from .canonical import PRECOMMIT_TYPE
from .timestamp import decode_timestamp, encode_timestamp
from .vote import Vote

__all__ = [
    "BLOCK_ID_FLAG_ABSENT",
    "BLOCK_ID_FLAG_COMMIT",
    "BLOCK_ID_FLAG_NIL",
    "CommitSig",
    "Commit",
    "MAX_COMMIT_OVERHEAD_BYTES",
    "MAX_COMMIT_SIG_BYTES",
    "max_commit_bytes",
]

# BlockIDFlag enum (reference: types/block.go:550-558)
BLOCK_ID_FLAG_ABSENT = 1  # no vote was received from this validator
BLOCK_ID_FLAG_COMMIT = 2  # voted for the committed block
BLOCK_ID_FLAG_NIL = 3  # voted nil

MAX_COMMIT_OVERHEAD_BYTES = 94  # reference: types/block.go:597
MAX_COMMIT_SIG_BYTES = 109  # reference: types/block.go:600

MAX_SIGNATURE_SIZE = 64

# Process-wide commit-mutation epoch. Every Commit memo (sign-bytes
# rows, flags array, hash, splice templates, fingerprint token) is
# pinned to the token stored here when it was built; any POST-INIT
# assignment to a Commit or CommitSig wire field replaces the token
# (one atomic STORE_SUBSCR — no read-modify-write), so every memo in
# the process re-validates lazily on next access. In production commits
# are immutable after construction (nothing in the package assigns a
# CommitSig field post-init), so the token never moves and the check is
# one `is` comparison; tests that mutate in place (forged-signature /
# mutated-timestamp safety tests) invalidate conservatively across ALL
# commits, which is always sound — a cleared memo is just rebuilt.
# In-place mutation of the `signatures` LIST (append/slice assignment)
# is not observable here and remains unsupported, exactly as the
# pre-existing _hash/_sign_templates memos already assumed.
# tmrace: race-ok — single atomic list-slot store of a fresh token;
# concurrent bumps each publish a token unequal to every pinned memo,
# so any interleaving invalidates (the conservative direction)
_MUT_EPOCH = [object()]


def max_commit_bytes(val_count: int) -> int:
    """reference: types/block.go:621-625."""
    proto_encoding_overhead = 2
    return MAX_COMMIT_OVERHEAD_BYTES + (
        (MAX_COMMIT_SIG_BYTES + proto_encoding_overhead) * val_count
    )


@dataclass
class CommitSig:
    block_id_flag: int = BLOCK_ID_FLAG_ABSENT
    validator_address: bytes = b""
    timestamp_ns: int = 0
    signature: bytes = b""

    def __setattr__(self, name: str, value) -> None:
        # a RE-assignment (the attribute already exists — dataclass
        # __init__ sets each field exactly once on a fresh instance)
        # mutates a signed record: bump the process-wide epoch so every
        # commit memo derived from CommitSig content re-validates
        if name in self.__dict__:
            _MUT_EPOCH[0] = object()
        object.__setattr__(self, name, value)

    @classmethod
    def absent(cls) -> "CommitSig":
        return cls(block_id_flag=BLOCK_ID_FLAG_ABSENT)

    @classmethod
    def for_block(
        cls, signature: bytes, val_addr: bytes, timestamp_ns: int
    ) -> "CommitSig":
        return cls(
            block_id_flag=BLOCK_ID_FLAG_COMMIT,
            validator_address=val_addr,
            timestamp_ns=timestamp_ns,
            signature=signature,
        )

    @classmethod
    def for_nil(
        cls, signature: bytes, val_addr: bytes, timestamp_ns: int
    ) -> "CommitSig":
        return cls(
            block_id_flag=BLOCK_ID_FLAG_NIL,
            validator_address=val_addr,
            timestamp_ns=timestamp_ns,
            signature=signature,
        )

    def is_absent(self) -> bool:
        return self.block_id_flag == BLOCK_ID_FLAG_ABSENT

    def is_for_block(self) -> bool:
        return self.block_id_flag == BLOCK_ID_FLAG_COMMIT

    def vote_block_id(self, commit_block_id: BlockID) -> BlockID:
        """BlockID this sig's vote was cast for (reference:
        types/block.go:661-674): the commit's for COMMIT, zero otherwise."""
        if self.block_id_flag == BLOCK_ID_FLAG_COMMIT:
            return commit_block_id
        return BlockID()

    def validate_basic(self) -> None:
        if self.block_id_flag not in (
            BLOCK_ID_FLAG_ABSENT,
            BLOCK_ID_FLAG_COMMIT,
            BLOCK_ID_FLAG_NIL,
        ):
            raise ValueError(f"unknown BlockIDFlag: {self.block_id_flag}")
        if self.block_id_flag == BLOCK_ID_FLAG_ABSENT:
            if self.validator_address:
                raise ValueError("validator address is present")
            if self.timestamp_ns:
                raise ValueError("time is present")
            if self.signature:
                raise ValueError("signature is present")
        else:
            if len(self.validator_address) != 20:
                raise ValueError(
                    "expected ValidatorAddress size to be 20 bytes"
                )
            if not self.signature:
                raise ValueError("signature is missing")
            if len(self.signature) > MAX_SIGNATURE_SIZE:
                raise ValueError("signature is too big")

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.int(1, self.block_id_flag)
        w.bytes(2, self.validator_address)
        w.message(3, encode_timestamp(self.timestamp_ns))
        w.bytes(4, self.signature)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "CommitSig":
        r = FieldReader(data)
        ts = r.get(3)
        return cls(
            block_id_flag=r.uint(1),
            validator_address=r.bytes(2),
            timestamp_ns=decode_timestamp(ts) if ts is not None else 0,
            signature=r.bytes(4),
        )


@dataclass
class Commit:
    height: int = 0
    round: int = 0
    block_id: BlockID = field(default_factory=BlockID)
    signatures: List[CommitSig] = field(default_factory=list)

    _hash: Optional[bytes] = field(
        default=None, repr=False, compare=False
    )
    # (chain_id, for_block) -> VoteSignTemplate; see vote_sign_bytes
    _sign_templates: Optional[dict] = field(
        default=None, repr=False, compare=False
    )
    # np.uint8 BlockIDFlags per signature; see block_id_flags_array
    _flags_memo: Optional[object] = field(
        default=None, repr=False, compare=False
    )
    # chain_id -> list of Optional[bytes] sign-bytes rows (None at
    # absent or not-yet-encoded indexes); see sign_bytes_batch
    _sb_rows: Optional[dict] = field(
        default=None, repr=False, compare=False
    )
    # chain_ids whose _sb_rows entry covers every non-absent index
    _sb_complete: Optional[set] = field(
        default=None, repr=False, compare=False
    )
    # content-identity token; see fingerprint_token
    _fp_token: Optional[object] = field(
        default=None, repr=False, compare=False
    )
    # the _MUT_EPOCH token the memos above were built under
    _memo_epoch: Optional[object] = field(
        default=None, repr=False, compare=False
    )

    # wire fields: a post-init assignment to one of these mutates the
    # signed record the memos were derived from
    _WIRE_FIELDS = frozenset({"height", "round", "block_id", "signatures"})

    def __setattr__(self, name: str, value) -> None:
        if name in self._WIRE_FIELDS and name in self.__dict__:
            _MUT_EPOCH[0] = object()
        object.__setattr__(self, name, value)

    def _memos_fresh(self) -> None:
        """Pin the memos to the current mutation epoch, dropping them
        all when ANY commit/sig field was re-assigned since they were
        built (see _MUT_EPOCH). Called at the top of every memoized
        accessor; the warm-path cost is one `is` comparison."""
        epoch = _MUT_EPOCH[0]
        if self._memo_epoch is not epoch:
            self._hash = None
            self._sign_templates = None
            self._flags_memo = None
            self._sb_rows = None
            self._sb_complete = None
            self._fp_token = None
            self._memo_epoch = epoch

    def invalidate_memos(self) -> None:
        """Drop every memo on THIS commit (bench cold rows, tests).
        Production code never needs this — memos self-invalidate on
        field mutation via the epoch."""
        self._memo_epoch = None
        self._memos_fresh()

    def fingerprint_token(self):
        """Content-identity token for the commit-level verification
        memo (types/validation.py): a unique object created lazily and
        REPLACED whenever any commit/sig field mutates, so a sigcache
        entry keyed on it can never alias different commit contents —
        unlike id(), a dead token is unreachable rather than reusable,
        and unlike a content digest it costs nothing to compare. The
        soundness argument is the same immutability-after-construction
        property every other memo here relies on, machine-checked by
        `scripts/lint.py --memo-audit` (docs/static_analysis.md)."""
        self._memos_fresh()
        if self._fp_token is None:
            self._fp_token = object()
        return self._fp_token

    def size(self) -> int:
        return len(self.signatures)

    def is_commit(self) -> bool:
        return len(self.signatures) != 0

    def bit_array(self) -> BitArray:
        ba = BitArray(len(self.signatures))
        for i, cs in enumerate(self.signatures):
            ba.set(i, not cs.is_absent())
        return ba

    def block_id_flags_array(self):
        """Per-signature BlockIDFlags as a read-only np.uint8 array,
        memoized — a Commit's signature list never changes after
        construction (the same property _hash and _sign_templates rely
        on). The vectorized VerifyCommit tally masks validator powers
        with it. Returns None when any flag is outside uint8 range
        (from_proto reads an unbounded varint): callers must fall back
        to the scalar loop so a hostile commit gets the reference
        InvalidCommitError, not an OverflowError from the memo."""
        self._memos_fresh()
        if self._flags_memo is None:
            import numpy as np

            try:
                # widen to int64 and range-check explicitly: fromiter
                # straight into uint8 raises on out-of-range only on
                # numpy >= 2 — numpy 1.x wraps modulo 256, which would
                # silently reclassify flag 257 as ABSENT and skip its
                # signature. int64 still overflows (and raises on both
                # majors) for varints past 2**63, hence the except.
                arr = np.fromiter(
                    (cs.block_id_flag for cs in self.signatures),
                    dtype=np.int64,
                    count=len(self.signatures),
                )
            except (OverflowError, ValueError):
                return None
            if arr.size and (arr.min() < 0 or arr.max() > 0xFF):
                return None
            arr = arr.astype(np.uint8)
            arr.setflags(write=False)
            self._flags_memo = arr
        return self._flags_memo

    def get_vote(self, val_idx: int) -> Vote:
        """Reconstruct the precommit vote at a validator index
        (reference: types/block.go:793-805)."""
        cs = self.signatures[val_idx]
        return Vote(
            type=PRECOMMIT_TYPE,
            height=self.height,
            round=self.round,
            block_id=cs.vote_block_id(self.block_id),
            timestamp_ns=cs.timestamp_ns,
            validator_address=cs.validator_address,
            validator_index=val_idx,
            signature=cs.signature,
        )

    def _sign_template(self, chain_id: str, for_block: bool):
        """Cached per-(chain_id, block-id-flag) splice template: only
        the timestamp varies between a commit's signatures, and the
        full proto-marshal path costs ~14 us/vote — the dominant host
        cost of a large VerifyCommit (types/validation.go:152 analog)."""
        from .canonical import VoteSignTemplate

        self._memos_fresh()
        if self._sign_templates is None:
            self._sign_templates = {}
        tpl = self._sign_templates.get((chain_id, for_block))
        if tpl is None:
            tpl = VoteSignTemplate(
                chain_id,
                PRECOMMIT_TYPE,
                self.height,
                self.round,
                self.block_id if for_block else BlockID(),
            )
            self._sign_templates[(chain_id, for_block)] = tpl
        return tpl

    def _rows_for(self, chain_id: str) -> List[Optional[bytes]]:
        """The per-chain sign-bytes row memo, allocated on first use.
        Callers must have run _memos_fresh() this access."""
        if self._sb_rows is None:
            self._sb_rows = {}
            self._sb_complete = set()
        rows = self._sb_rows.get(chain_id)
        if rows is None:
            rows = self._sb_rows[chain_id] = [None] * len(self.signatures)
        return rows

    def vote_sign_bytes(self, chain_id: str, val_idx: int) -> bytes:
        """Sign-bytes of the vote at a validator index. Byte-identical
        to get_vote(i).sign_bytes(chain_id) (tests/test_encoding.py).

        Memoized per (chain_id, index) in the same rows list
        sign_bytes_batch fills: a commit's sign-bytes are a pure
        function of (type, height, round, block_id, timestamp,
        chain_id) — machine-proved deterministic by tmcheck's taint
        gate (docs/static_analysis.md) — and the inputs are frozen
        after construction (mutation drops the memo via _MUT_EPOCH).
        gossip-verify, LastCommit re-verification, and the light
        client's double-verify each re-encoded the same rows before;
        now only the first pass pays, and only for the indexes it
        actually visits (early-exit variants never encode discarded
        rows)."""
        self._memos_fresh()
        cs = self.signatures[val_idx]
        if cs.block_id_flag == BLOCK_ID_FLAG_ABSENT:
            # not memoized: sign_bytes_batch's contract keeps absent
            # rows None, and no verification path requests them
            tpl = self._sign_template(chain_id, False)
            return tpl.sign_bytes(cs.timestamp_ns)
        rows = self._rows_for(chain_id)
        row = rows[val_idx]
        if row is None:
            tpl = self._sign_template(
                chain_id, cs.block_id_flag == BLOCK_ID_FLAG_COMMIT
            )
            row = rows[val_idx] = tpl.sign_bytes(cs.timestamp_ns)
        return row

    def sign_bytes_batch(self, chain_id: str) -> List[Optional[bytes]]:
        """Sign-bytes for every non-absent signature in one pass
        (None at absent indexes). The batch VerifyCommit path uses
        this instead of per-index vote_sign_bytes: template splicing
        plus the tight per-timestamp loop beats the full marshal ~10x
        at 10k signatures.

        Memoized per chain_id (see vote_sign_bytes for the soundness
        argument): the returned list is SHARED with the memo and must
        be treated read-only by callers. Warm verification paths
        (steady-state LastCommit, light-client double-verify) hit this
        memo and perform zero canonical encodes — the tier-1
        counting-stub guard in tests/test_sigcache.py pins that."""
        self._memos_fresh()
        sigs = self.signatures
        if self._sb_complete is not None and chain_id in self._sb_complete:
            return self._sb_rows[chain_id]
        out = self._rows_for(chain_id)
        for for_block in (True, False):
            idxs = [
                i
                for i, cs in enumerate(sigs)
                if not cs.is_absent()
                and (cs.block_id_flag == BLOCK_ID_FLAG_COMMIT) == for_block
                and out[i] is None
            ]
            if not idxs:
                continue
            tpl = self._sign_template(chain_id, for_block)
            rows = tpl.sign_bytes_batch(
                [sigs[i].timestamp_ns for i in idxs]
            )
            for i, row in zip(idxs, rows):
                out[i] = row
        self._sb_complete.add(chain_id)
        return out

    def validate_basic(self) -> None:
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if self.height >= 1:
            if self.block_id.is_zero():
                raise ValueError("commit cannot be for nil block")
            if not self.signatures:
                raise ValueError("no signatures in commit")
            for i, cs in enumerate(self.signatures):
                try:
                    cs.validate_basic()
                except ValueError as e:
                    raise ValueError(f"wrong CommitSig #{i}: {e}") from e

    def hash(self) -> bytes:
        """Merkle root over marshalled CommitSigs
        (reference: types/block.go:902-921)."""
        self._memos_fresh()
        if self._hash is None:
            self._hash = merkle.hash_from_byte_slices(
                [cs.to_proto() for cs in self.signatures]
            )
        return self._hash

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.int(1, self.height)
        w.int(2, self.round)
        w.message(3, self.block_id.to_proto())  # nullable=false
        for cs in self.signatures:
            w.message(4, cs.to_proto())
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "Commit":
        height = 0
        round_ = 0
        block_id = BlockID()
        sigs: List[CommitSig] = []
        for f, _wt, v in iter_fields(data):
            if f == 1:
                height = v
            elif f == 2:
                round_ = v
            elif f == 3:
                block_id = BlockID.from_proto(v)
            elif f == 4:
                sigs.append(CommitSig.from_proto(v))
        return cls(
            height=height, round=round_, block_id=block_id, signatures=sigs
        )
