"""BlockID and PartSetHeader.

Reference: types/block.go (BlockID, PartSetHeader structs and their
proto round-trips, proto/tendermint/types/types.pb.go:100-101,213-214).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import tmhash
from ..encoding.proto import FieldReader, ProtoWriter

__all__ = ["PartSetHeader", "BlockID"]


@dataclass(frozen=True)
class PartSetHeader:
    total: int = 0
    hash: bytes = b""

    def is_zero(self) -> bool:
        return self.total == 0 and len(self.hash) == 0

    def validate_basic(self) -> None:
        if self.hash and len(self.hash) != tmhash.SIZE:
            raise ValueError(
                f"PartSetHeader hash must be {tmhash.SIZE} bytes"
            )
        if self.total < 0:
            raise ValueError("PartSetHeader total cannot be negative")

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.uint(1, self.total)
        w.bytes(2, self.hash)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "PartSetHeader":
        r = FieldReader(data)
        return cls(total=r.uint(1), hash=r.bytes(2))


@dataclass(frozen=True)
class BlockID:
    hash: bytes = b""
    part_set_header: PartSetHeader = field(default_factory=PartSetHeader)

    def is_zero(self) -> bool:
        """Neither a block nil-vote target nor a complete ID."""
        return len(self.hash) == 0 and self.part_set_header.is_zero()

    def is_complete(self) -> bool:
        return (
            len(self.hash) == tmhash.SIZE
            and self.part_set_header.total > 0
            and len(self.part_set_header.hash) == tmhash.SIZE
        )

    def validate_basic(self) -> None:
        if self.hash and len(self.hash) != tmhash.SIZE:
            raise ValueError(f"BlockID hash must be {tmhash.SIZE} bytes")
        self.part_set_header.validate_basic()

    def key(self) -> bytes:
        """Map key (reference: types/block.go BlockID.Key)."""
        return self.hash + self.part_set_header.to_proto()

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.bytes(1, self.hash)
        w.message(2, self.part_set_header.to_proto())
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "BlockID":
        r = FieldReader(data)
        psh = r.get(2)
        return cls(
            hash=r.bytes(1),
            part_set_header=(
                PartSetHeader.from_proto(psh)
                if psh is not None
                else PartSetHeader()
            ),
        )
