"""Evidence of Byzantine behavior.

Reference: types/evidence.go — DuplicateVoteEvidence (:33-200),
LightClientAttackEvidence (:230-480), EvidenceList (:540-580); proto
field numbers proto/tendermint/types/evidence.pb.go.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Union

from ..crypto import merkle, tmhash
from ..encoding.proto import (
    FieldReader,
    ProtoWriter,
    encode_varint,
    encode_zigzag,
    iter_fields,
)
from .timestamp import decode_timestamp, encode_timestamp
from .validator import Validator, ValidatorSet
from .vote import Vote

__all__ = [
    "DuplicateVoteEvidence",
    "LightClientAttackEvidence",
    "Evidence",
    "evidence_to_proto",
    "evidence_from_proto",
    "evidence_list_hash",
]


@dataclass
class DuplicateVoteEvidence:
    """Two conflicting votes by one validator at the same H/R/S
    (reference: types/evidence.go:33-200). vote_a is the one with the
    lexicographically smaller BlockID key."""

    vote_a: Vote
    vote_b: Vote
    total_voting_power: int = 0
    validator_power: int = 0
    timestamp_ns: int = 0

    @classmethod
    def from_votes(
        cls,
        vote1: Vote,
        vote2: Vote,
        block_time_ns: int,
        val_set: ValidatorSet,
    ) -> "DuplicateVoteEvidence":
        """reference: types/evidence.go:58-100 (NewDuplicateVoteEvidence)."""
        if vote1 is None or vote2 is None:
            raise ValueError("missing vote")
        idx, val = val_set.get_by_address(vote1.validator_address)
        if idx == -1:
            raise ValueError("validator not in validator set")
        if vote1.block_id.key() < vote2.block_id.key():
            vote_a, vote_b = vote1, vote2
        else:
            vote_a, vote_b = vote2, vote1
        return cls(
            vote_a=vote_a,
            vote_b=vote_b,
            total_voting_power=val_set.total_voting_power(),
            validator_power=val.voting_power,
            timestamp_ns=block_time_ns,
        )

    def height(self) -> int:
        return self.vote_a.height

    def bytes(self) -> bytes:
        return self.to_proto()

    def hash(self) -> bytes:
        return tmhash.sum256(self.bytes())

    def validate_basic(self) -> None:
        if self.vote_a is None or self.vote_b is None:
            raise ValueError("empty duplicate vote evidence")
        self.vote_a.validate_basic()
        self.vote_b.validate_basic()
        if self.vote_a.block_id.key() >= self.vote_b.block_id.key():
            raise ValueError(
                "duplicate votes in invalid order (or the same block id)"
            )

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.message(1, self.vote_a.to_proto())
        w.message(2, self.vote_b.to_proto())
        w.int(3, self.total_voting_power)
        w.int(4, self.validator_power)
        w.message(5, encode_timestamp(self.timestamp_ns))
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "DuplicateVoteEvidence":
        r = FieldReader(data)
        ts = r.get(5)
        return cls(
            vote_a=Vote.from_proto(r.get(1, b"")),
            vote_b=Vote.from_proto(r.get(2, b"")),
            total_voting_power=r.int64(3),
            validator_power=r.int64(4),
            timestamp_ns=decode_timestamp(ts) if ts is not None else 0,
        )


@dataclass
class LightClientAttackEvidence:
    """A conflicting light block trace
    (reference: types/evidence.go:230-480)."""

    conflicting_block: "object"  # types.light.LightBlock
    common_height: int = 0
    byzantine_validators: List[Validator] = field(default_factory=list)
    total_voting_power: int = 0
    timestamp_ns: int = 0

    def height(self) -> int:
        return self.common_height

    def bytes(self) -> bytes:
        return self.to_proto()

    def hash(self) -> bytes:
        """reference: types/evidence.go:359-366 — header hash (with its
        final byte dropped by the reference's off-by-one copy, kept for
        parity) + varint common height."""
        header_hash = self.conflicting_block.signed_header.hash()
        buf = bytearray(tmhash.SIZE)
        buf[: tmhash.SIZE - 1] = header_hash[: tmhash.SIZE - 1]
        return tmhash.sum256(
            bytes(buf) + encode_varint(encode_zigzag(self.common_height))
        )

    def validate_basic(self) -> None:
        if self.conflicting_block is None:
            raise ValueError("conflicting block is nil")
        if self.common_height <= 0:
            raise ValueError("negative or zero common height")
        sh = self.conflicting_block.signed_header
        if sh is None or sh.header is None:
            raise ValueError("conflicting block missing header")

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.message(1, self.conflicting_block.to_proto())
        w.int(2, self.common_height)
        for v in self.byzantine_validators:
            w.message(3, v.to_proto())
        w.int(4, self.total_voting_power)
        w.message(5, encode_timestamp(self.timestamp_ns))
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "LightClientAttackEvidence":
        from .light import LightBlock

        cb = None
        common_height = 0
        byz: List[Validator] = []
        tvp = 0
        ts = 0
        for f, _wt, v in iter_fields(data):
            if f == 1:
                cb = LightBlock.from_proto(v)
            elif f == 2:
                common_height = v
            elif f == 3:
                byz.append(Validator.from_proto(v))
            elif f == 4:
                tvp = v
            elif f == 5:
                ts = decode_timestamp(v)
        return cls(
            conflicting_block=cb,
            common_height=common_height,
            byzantine_validators=byz,
            total_voting_power=tvp,
            timestamp_ns=ts,
        )


Evidence = Union[DuplicateVoteEvidence, LightClientAttackEvidence]


def evidence_to_proto(ev: Evidence) -> bytes:
    """tendermint.types.Evidence oneof wrapper (duplicate=1, lca=2)."""
    w = ProtoWriter()
    if isinstance(ev, DuplicateVoteEvidence):
        w.message(1, ev.to_proto())
    elif isinstance(ev, LightClientAttackEvidence):
        w.message(2, ev.to_proto())
    else:
        raise TypeError(f"unknown evidence type {type(ev)}")
    return w.finish()


def evidence_from_proto(data: bytes) -> Evidence:
    r = FieldReader(data)
    dve = r.get(1)
    if dve is not None:
        return DuplicateVoteEvidence.from_proto(dve)
    lca = r.get(2)
    if lca is not None:
        return LightClientAttackEvidence.from_proto(lca)
    raise ValueError("evidence proto is empty")


def evidence_list_hash(evidence: List[Evidence]) -> bytes:
    """Merkle root over evidence bytes
    (reference: types/evidence.go:558-569)."""
    return merkle.hash_from_byte_slices([ev.bytes() for ev in evidence])
