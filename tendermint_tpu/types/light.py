"""SignedHeader and LightBlock.

Reference: types/light.go (LightBlock :13-100, SignedHeader :120-180),
proto/tendermint/types/types.pb.go:800-801,852-853.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..encoding.proto import FieldReader, ProtoWriter
from .commit import Commit
from .header import Header
from .validator import ValidatorSet

__all__ = [
    "SignedHeader",
    "LightBlock",
    "LightBlocksRequest",
    "LightBlocksResponse",
]


@dataclass
class SignedHeader:
    header: Optional[Header] = None
    commit: Optional[Commit] = None

    @property
    def height(self) -> int:
        return self.header.height if self.header else 0

    def hash(self) -> bytes:
        return self.header.hash() if self.header else b""

    def validate_basic(self, chain_id: str) -> None:
        """reference: types/light.go SignedHeader.ValidateBasic."""
        if self.header is None:
            raise ValueError("missing header")
        if self.commit is None:
            raise ValueError("missing commit")
        self.header.validate_basic()
        if self.header.chain_id != chain_id:
            raise ValueError(
                f"header belongs to another chain {self.header.chain_id!r}"
            )
        self.commit.validate_basic()
        if self.header.height != self.commit.height:
            raise ValueError("header and commit height mismatch")
        if self.header.hash() != self.commit.block_id.hash:
            raise ValueError("commit signs block with wrong hash")

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        if self.header is not None:
            w.message(1, self.header.to_proto())
        if self.commit is not None:
            w.message(2, self.commit.to_proto())
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "SignedHeader":
        r = FieldReader(data)
        h = r.get(1)
        c = r.get(2)
        return cls(
            header=Header.from_proto(h) if h is not None else None,
            commit=Commit.from_proto(c) if c is not None else None,
        )


@dataclass
class LightBlock:
    signed_header: Optional[SignedHeader] = None
    validator_set: Optional[ValidatorSet] = None

    @property
    def height(self) -> int:
        return self.signed_header.height if self.signed_header else 0

    def validate_basic(self, chain_id: str) -> None:
        """reference: types/light.go LightBlock.ValidateBasic."""
        if self.signed_header is None:
            raise ValueError("missing signed header")
        if self.validator_set is None:
            raise ValueError("missing validator set")
        self.signed_header.validate_basic(chain_id)
        self.validator_set.validate_basic()
        if (
            self.signed_header.header.validators_hash
            != self.validator_set.hash()
        ):
            raise ValueError(
                "expected validator hash of header to match validator set hash"
            )

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        if self.signed_header is not None:
            w.message(1, self.signed_header.to_proto())
        if self.validator_set is not None:
            w.message(2, self.validator_set.to_proto())
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "LightBlock":
        r = FieldReader(data)
        sh = r.get(1)
        vs = r.get(2)
        return cls(
            signed_header=(
                SignedHeader.from_proto(sh) if sh is not None else None
            ),
            validator_set=(
                ValidatorSet.from_proto(vs) if vs is not None else None
            ),
        )


@dataclass
class LightBlocksRequest:
    """Bulk light-block fetch: an ascending height range plus the
    client's own page bound (framework message — the reference has no
    bulk form; the JSON-RPC `light_blocks` route carries the same
    fields as params, and the server clamps the page regardless of
    what the request asks for)."""

    min_height: int = 0
    max_height: int = 0
    max_blocks: int = 0

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.int(1, self.min_height)
        w.int(2, self.max_height)
        w.int(3, self.max_blocks)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "LightBlocksRequest":
        r = FieldReader(data)
        return cls(
            min_height=r.int64(1),
            max_height=r.int64(2),
            max_blocks=r.int64(3),
        )


@dataclass
class LightBlocksResponse:
    """One served page of the bulk fetch: consecutive LightBlocks in
    ascending height order plus the serving store's current tip, so a
    clamped client knows whether another page exists without a status
    round-trip."""

    light_blocks: List[LightBlock] = field(default_factory=list)
    last_height: int = 0

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        for lb in self.light_blocks:
            w.message(1, lb.to_proto())
        w.int(2, self.last_height)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "LightBlocksResponse":
        r = FieldReader(data)
        return cls(
            light_blocks=[
                LightBlock.from_proto(b) for b in r.get_all(1)
            ],
            last_height=r.int64(2),
        )
