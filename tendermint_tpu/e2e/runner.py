"""Manifest-driven e2e testnet runner.

The reference's runner (test/e2e/runner/{setup,start,load,perturb,
wait,test,benchmark}.go) builds docker-compose networks from TOML
manifests, applies transaction load and fault perturbations, waits for
convergence, then runs black-box invariant tests against live RPC. This
runner keeps that phase structure but hosts the network in-process:
real Nodes over a MemoryNetwork, so the whole schedule — delayed
starts, double-signers, kills, disconnects — runs inside one asyncio
loop, deterministically and fast enough for CI.

Phases (all driven from `Runner.run()`):
  setup     — workdir, genesis, per-node config/keys (setup.go)
  start     — boot start_at=0 nodes; late nodes join at their heights
              (start.go)
  load      — background tx generator at `load.tx_rate` (load.go)
  perturb   — kill/restart/disconnect/pause at scheduled heights
              (perturb.go)
  wait      — every live node reaches target_height (wait.go)
  test      — invariants: common-prefix hash equality, app-hash
              agreement, committed evidence for every misbehaving node,
              tx inclusion under load (test/e2e/tests/)
  benchmark — block-interval avg/stddev/min/max over the run
              (benchmark.go:14-23)
"""

from __future__ import annotations

import asyncio
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..config import Config
from ..consensus.msgs import VoteMessage
from ..crypto.ed25519 import PrivKeyEd25519
from ..node import NodeKey, make_node
from ..p2p.transport import MemoryNetwork, MemoryTransport
from ..p2p.types import Envelope
from ..privval import FilePV, MockPV
from ..types.block_id import BlockID, PartSetHeader
from ..types.canonical import PREVOTE_TYPE
from ..types.evidence import DuplicateVoteEvidence
from ..types.genesis import GenesisDoc, GenesisValidator
from ..types.vote import Vote
from .manifest import Manifest, NodeSpec

__all__ = ["Runner", "RunReport"]


@dataclass
class RunReport:
    """Outcome of a manifest run (returned by Runner.run())."""

    reached_height: int = 0
    blocks: int = 0
    interval_avg: float = 0.0
    interval_stddev: float = 0.0
    interval_min: float = 0.0
    interval_max: float = 0.0
    txs_submitted: int = 0
    txs_committed: int = 0
    evidence_heights: Dict[str, int] = field(default_factory=dict)
    state_synced: Dict[str, bool] = field(default_factory=dict)
    failures: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.failures


class _NodeHandle:
    def __init__(self, spec: NodeSpec, cfg: Config, priv=None):
        self.spec = spec
        self.cfg = cfg
        self.priv = priv  # validator key, if any
        self.node = None
        self.started = False
        # sticky across kill/restart: the flag lives on the Node
        # instance, and a restarted node (with history on disk) skips
        # statesync by design
        self.state_synced_once = False

    def note_sync(self) -> None:
        if self.node is not None and getattr(
            self.node, "genesis_state_synced", False
        ):
            self.state_synced_once = True

    @property
    def live(self) -> bool:
        return self.node is not None and self.node.is_running


class Runner:
    def __init__(
        self, manifest: Manifest, home: str, timeout: float = 240.0
    ):
        self.m = manifest
        self.home = home
        self.timeout = timeout
        self.net = MemoryNetwork()
        self.handles: Dict[str, _NodeHandle] = {}
        self._node_ids: Dict[str, str] = {}
        self._tx_seq = 0
        self._resume_tasks: List[asyncio.Task] = []
        # nodes currently isolated by the partition perturbation
        # (composable: each isolated node is its own group)
        self._partitioned: set = set()
        self.report = RunReport()

    # -- setup (reference: test/e2e/runner/setup.go) --

    def setup(self) -> None:
        m = self.m
        privs = {
            name: PrivKeyEd25519.from_seed(
                name.encode().ljust(32, b"\x9e")[:32]
            )
            for name in m.validators
        }
        genesis = GenesisDoc(
            chain_id=m.chain_id,
            genesis_time_ns=time.time_ns(),
            initial_height=m.initial_height,
            validators=[
                GenesisValidator(pub_key=privs[n].pub_key(), power=p)
                for n, p in sorted(m.validators.items())
            ],
        )
        for name, spec in self.m.sorted_nodes():
            cfg = Config()
            cfg.base.home = os.path.join(self.home, name)
            cfg.base.chain_id = m.chain_id
            cfg.base.db_backend = spec.database
            cfg.base.mode = spec.mode
            cfg.consensus.timeout_propose = 2.0
            cfg.consensus.timeout_prevote = 1.0
            cfg.consensus.timeout_precommit = 1.0
            cfg.consensus.timeout_commit = 0.2
            cfg.consensus.peer_gossip_sleep_duration = 0.01
            cfg.rpc.laddr = "tcp://127.0.0.1:0"
            cfg.p2p.laddr = f"{name}:26656"
            cfg.statesync.enable = spec.state_sync
            if spec.state_sync:
                cfg.statesync.discovery_time = 1.0
                cfg.statesync.chunk_request_timeout = 5.0
            cfg.ensure_dirs()
            genesis.save_as(cfg.base.path(cfg.base.genesis_file))
            priv = privs.get(name)
            if priv is not None:
                FilePV.from_priv_key(
                    priv,
                    cfg.base.path(cfg.priv_validator.key_file),
                    cfg.base.path(cfg.priv_validator.state_file),
                ).save()
            self.handles[name] = _NodeHandle(spec, cfg, priv)
            self._node_ids[name] = NodeKey.load_or_generate(
                cfg.base.path(cfg.base.node_key_file)
            ).node_id
        all_names = list(self.handles)
        for name, h in self.handles.items():
            h.cfg.p2p.persistent_peers = ",".join(
                f"{self._node_ids[o]}@{o}:26656"
                for o in all_names
                if o != name
            )

    # -- start (reference: test/e2e/runner/start.go) --

    # snapshots are advertised by every app when anyone will state
    # sync (the reference e2e app's snapshot_interval manifest knob)
    SNAPSHOT_INTERVAL = 2

    def _make_app(self):
        if not any(s.state_sync for s in self.m.nodes.values()):
            return None  # make_node default app
        from ..abci.kvstore import KVStoreApplication

        return KVStoreApplication(
            snapshot_interval=self.SNAPSHOT_INTERVAL
        )

    async def _start_node(self, name: str) -> None:
        h = self.handles[name]
        if h.spec.state_sync and h.node is None:
            self._seed_state_sync_trust(h)
        h.node = make_node(
            h.cfg,
            app=self._make_app(),
            transport=MemoryTransport(self.net, f"{name}:26656"),
        )
        self._arm_misbehaviors(h)
        await h.node.start()
        h.started = True

    def _seed_state_sync_trust(self, h: _NodeHandle) -> None:
        """Anchor the late joiner's state-sync trust to a live node's
        chain (the operator-supplied trust root in production)."""
        for other in self.handles.values():
            if other.live and other.node.block_store.height() >= 1:
                bm = other.node.block_store.load_block_meta(1)
                if bm is not None:
                    h.cfg.statesync.trust_height = 1
                    h.cfg.statesync.trust_hash = (
                        bm.block_id.hash.hex()
                    )
                    return

    def _arm_misbehaviors(self, h: _NodeHandle) -> None:
        at = h.spec.misbehaviors.get("double-prevote")
        if at is None or h.priv is None:
            return
        node = h.node
        node.privval = MockPV(h.priv)  # no double-sign protection
        addr = h.priv.pub_key().address()
        fired = set()

        def arm() -> None:
            cs = node.consensus
            reactor = node.consensus_reactor
            orig = cs.do_prevote

            async def evil_prevote(height, round_):
                await orig(height, round_)
                if height < at or height in fired:
                    return
                if cs.rs.proposal_block is None:
                    return
                fired.add(height)
                order = {
                    v.address: i
                    for i, v in enumerate(cs.rs.validators.validators)
                }
                vote = Vote(
                    type=PREVOTE_TYPE,
                    height=height,
                    round=round_,
                    block_id=BlockID(
                        hash=b"\xe1" * 32,
                        part_set_header=PartSetHeader(
                            total=1, hash=b"\xe2" * 32
                        ),
                    ),
                    timestamp_ns=time.time_ns(),
                    validator_address=addr,
                    validator_index=order[addr],
                )
                await node.privval.sign_vote(self.m.chain_id, vote)
                await reactor.vote_ch.send(
                    Envelope(
                        message=VoteMessage(vote=vote), broadcast=True
                    )
                )

            cs.do_prevote = evil_prevote

        # consensus objects exist only after start; patch lazily
        self._post_start = getattr(self, "_post_start", {})
        self._post_start[h.spec.name] = arm

    # -- load (reference: test/e2e/runner/load.go) --

    async def _load_loop(self) -> None:
        rate = self.m.load.tx_rate
        if rate <= 0:
            return
        period = 1.0 / rate
        i = 0
        while True:
            await asyncio.sleep(period)
            live = [h for h in self.handles.values() if h.live]
            if not live:
                continue
            h = live[i % len(live)]
            i += 1
            self._tx_seq += 1
            key = f"load-{self._tx_seq}"
            val = os.urandom(max(1, self.m.load.tx_size // 2)).hex()
            tx = f"{key}={val}".encode()[: self.m.load.tx_size]
            try:
                await h.node.mempool.check_tx(tx)
                self.report.txs_submitted += 1
            except Exception:
                pass  # full mempool / node stopping: load is best-effort

    # -- perturb (reference: test/e2e/runner/perturb.go) --

    async def _apply_perturbation(self, name: str, action: str) -> None:
        h = self.handles[name]
        h.note_sync()
        if action == "kill":
            if h.live:
                await h.node.stop()
        elif action == "restart":
            if h.live:
                await h.node.stop()
            await self._start_node(name)
            self._run_post_start(name)
        elif action == "disconnect":
            if h.live:
                router = h.node.router
                for pid in list(router._peer_conns):
                    router._peer_down(pid)
        elif action == "partition":
            # real p2p-level cut via the runtime-mutable partition
            # sets (crypto/faults.py): the node keeps running and
            # serving RPC while every link to the rest drops frames.
            # Tracked as a SET of isolated nodes (same shape as the
            # process runner's partition.spec writer) so a second
            # partition composes with — instead of silently healing —
            # the first, and heal releases only ITS node.
            self._partitioned.add(name)
            self._set_partition_groups()
        elif action == "heal":
            self._partitioned.discard(name)
            self._set_partition_groups()
        elif action == "pause":
            if h.live:
                await h.node.stop()

                async def resume():
                    await asyncio.sleep(3.0)
                    if not h.live:
                        await self._start_node(name)
                        self._run_post_start(name)

                self._resume_tasks.append(
                    asyncio.get_running_loop().create_task(resume())
                )

    def _set_partition_groups(self) -> None:
        """Render the isolated-node set: each isolated node its OWN
        group (cut from each other too), the remainder one connected
        group; empty set heals. Labels are node IDs."""
        from ..crypto import faults

        def nid(name):
            return self.handles[name].node.node_info.node_id

        isolated = sorted(self._partitioned)
        rest = [n for n in self.handles if n not in self._partitioned]
        groups = [[nid(n)] for n in isolated]
        if isolated and rest:
            groups.append([nid(n) for n in rest])
        faults.set_partition(
            "|".join(",".join(g) for g in groups) if isolated else ""
        )

    def _run_post_start(self, name: str) -> None:
        hook = getattr(self, "_post_start", {}).get(name)
        if hook and self.handles[name].live:
            hook()

    # -- orchestration --

    def _network_height(self) -> int:
        return max(
            (
                h.node.block_store.height()
                for h in self.handles.values()
                if h.live
            ),
            default=0,
        )

    async def run(self) -> RunReport:
        self.setup()
        for name, h in self.m.sorted_nodes():
            if self.handles[name].spec.start_at == 0:
                await self._start_node(name)
        for name in self.handles:
            self._run_post_start(name)

        load_task = asyncio.get_running_loop().create_task(
            self._load_loop()
        )
        pending_starts = {
            name: h.spec.start_at
            for name, h in self.handles.items()
            if h.spec.start_at > 0
        }
        schedule: List[tuple] = []
        for name, h in self.handles.items():
            for p in h.spec.perturb:
                schedule.append((p.height, name, p.action))
        schedule.sort()

        deadline = time.monotonic() + self.timeout
        try:
            while True:
                if time.monotonic() > deadline:
                    self.report.failures.append(
                        f"timeout before height {self.m.target_height} "
                        f"(at {self._network_height()})"
                    )
                    break
                await asyncio.sleep(0.25)
                height = self._network_height()
                for name, at in list(pending_starts.items()):
                    if height >= at:
                        del pending_starts[name]
                        await self._start_node(name)
                        self._run_post_start(name)
                while schedule and schedule[0][0] <= height:
                    _, name, action = schedule.pop(0)
                    await self._apply_perturbation(name, action)
                if (
                    height >= self.m.target_height
                    and not pending_starts
                    and not schedule
                ):
                    # every live node must individually converge
                    laggard = [
                        h
                        for h in self.handles.values()
                        if h.live
                        and h.node.block_store.height()
                        < self.m.target_height
                    ]
                    if not laggard:
                        break
        finally:
            load_task.cancel()
            for t in self._resume_tasks:
                t.cancel()
            await asyncio.gather(
                load_task, *self._resume_tasks, return_exceptions=True
            )

        self._check_invariants()
        self._benchmark()
        for h in self.handles.values():
            if h.live:
                await h.node.stop()
        return self.report

    # -- test (reference: test/e2e/tests/) --

    def _live_nodes(self):
        return [h for h in self.handles.values() if h.live]

    def _check_invariants(self) -> None:
        rep = self.report
        live = self._live_nodes()
        if not live:
            rep.failures.append("no live nodes at end of run")
            return
        rep.reached_height = min(
            h.node.block_store.height() for h in live
        )
        if rep.reached_height < self.m.target_height:
            rep.failures.append(
                f"converged height {rep.reached_height} < target "
                f"{self.m.target_height}"
            )
        # identical blocks across nodes over the common prefix
        ref = live[0]
        base = max(h.node.block_store.base() for h in live)
        for height in range(max(base, 1), rep.reached_height + 1):
            want = ref.node.block_store.load_block_meta(height)
            for h in live[1:]:
                got = h.node.block_store.load_block_meta(height)
                if got is None or want is None:
                    continue  # pruned / state-synced node
                if got.block_id.hash != want.block_id.hash:
                    rep.failures.append(
                        f"fork at height {height}: "
                        f"{h.spec.name} disagrees with {ref.spec.name}"
                    )
        # committed txs under load
        if self.m.load.tx_rate > 0:
            committed = 0
            for height in range(1, rep.reached_height + 1):
                block = ref.node.block_store.load_block(height)
                if block is not None:
                    committed += len(block.txs)
            rep.txs_committed = committed
            if rep.txs_submitted > 0 and committed == 0:
                rep.failures.append("load ran but no txs were committed")
        # every state_sync node must have restored from a snapshot
        # (not silently block-synced from genesis)
        for name, h in self.handles.items():
            if not h.spec.state_sync:
                continue
            h.note_sync()
            rep.state_synced[name] = h.state_synced_once
            if not h.state_synced_once:
                rep.failures.append(
                    f"{name} was configured for state sync but never "
                    "restored a snapshot"
                )
        # evidence for every double-signer
        for name, h in self.handles.items():
            if "double-prevote" not in h.spec.misbehaviors:
                continue
            addr = h.priv.pub_key().address()
            found = None
            for height in range(1, rep.reached_height + 1):
                block = ref.node.block_store.load_block(height)
                if block is None:
                    continue
                for ev in block.evidence:
                    if (
                        isinstance(ev, DuplicateVoteEvidence)
                        and ev.vote_a.validator_address == addr
                    ):
                        found = height
            if found is None:
                rep.failures.append(
                    f"no DuplicateVoteEvidence committed for {name}"
                )
            else:
                rep.evidence_heights[name] = found

    # -- benchmark (reference: test/e2e/runner/benchmark.go:14-23) --

    def _benchmark(self) -> None:
        live = self._live_nodes()
        if not live:
            return
        store = live[0].node.block_store
        times: List[int] = []
        for height in range(1, self.report.reached_height + 1):
            bm = store.load_block_meta(height)
            if bm is not None:
                times.append(bm.header.time_ns)
        if len(times) < 2:
            return
        deltas = [
            (b - a) / 1e9 for a, b in zip(times, times[1:])
        ]
        rep = self.report
        rep.blocks = len(deltas)
        rep.interval_avg = sum(deltas) / len(deltas)
        mean = rep.interval_avg
        rep.interval_stddev = (
            sum((d - mean) ** 2 for d in deltas) / len(deltas)
        ) ** 0.5
        rep.interval_min = min(deltas)
        rep.interval_max = max(deltas)


def run_manifest(
    manifest: Manifest, home: str, timeout: float = 240.0
) -> RunReport:
    """Convenience sync wrapper."""
    return asyncio.run(Runner(manifest, home, timeout=timeout).run())
