"""Randomized-but-deterministic manifest generation.

The reference CI generates permuted testnet manifests from a seeded
RNG (test/e2e/generator/generate.go) so every run explores a different
corner of {topology x sync modes x faults} while staying reproducible.
Same idea here: `generate(seed)` returns a list of Manifests covering
validator counts, databases, late joiners (block sync / state sync),
perturbations, and double-signers.
"""

from __future__ import annotations

import random
from typing import List

from .manifest import LoadSpec, Manifest, NodeSpec, Perturbation

__all__ = ["generate"]


def _gen_one(rng: random.Random, idx: int) -> Manifest:
    n_vals = rng.choice([2, 3, 4, 4])
    # every scheduled height is relative to the chain's first block so
    # an initial_height=1000 chain gets the same schedule shape
    ih = rng.choice([1, 1, 1000])
    m = Manifest(
        chain_id=f"gen-{idx}",
        initial_height=ih,
        target_height=ih + rng.randint(3, 5),
        validators={
            f"validator{i:02d}": rng.choice([5, 10, 10])
            for i in range(1, n_vals + 1)
        },
    )
    for name in m.validators:
        m.nodes[name] = NodeSpec(
            name=name,
            database=rng.choice(["memdb", "memdb", "sqlite"]),
        )
    # a late-joining full node exercising block sync (sometimes)
    if rng.random() < 0.5:
        m.nodes["full01"] = NodeSpec(
            name="full01",
            mode="full",
            start_at=ih + 1,
            database=rng.choice(["memdb", "sqlite"]),
        )
    # perturbations on a minority of validators
    if n_vals >= 4 and rng.random() < 0.6:
        victim = rng.choice(sorted(m.validators))
        action = rng.choice(["kill", "disconnect", "restart"])
        height = ih + rng.randint(1, 2)
        spec = m.nodes[victim]
        spec.perturb = [Perturbation(action=action, height=height)]
        if action == "kill":
            spec.perturb.append(
                Perturbation(action="restart", height=height + 1)
            )
    # a double-signer needs >3 validators to stay below 1/3 power
    if n_vals >= 4 and rng.random() < 0.4:
        byz = sorted(m.validators)[-1]
        m.nodes[byz].misbehaviors = {"double-prevote": ih + 1}
    if rng.random() < 0.5:
        m.load = LoadSpec(tx_rate=rng.choice([2.0, 5.0]), tx_size=64)
    m.validate()
    return m


def generate(seed: int, count: int = 8) -> List[Manifest]:
    rng = random.Random(seed)
    return [_gen_one(rng, i) for i in range(count)]
