"""Real-process e2e testnet runner.

The in-process runner (runner.py) hosts every node in one asyncio loop
— fast and deterministic, but its "kill" is a polite stop: WAL replay
after a hard kill mid-fsync, torn tails from a genuinely dead process,
and ABCI handshake replay against a surviving app server are never
exercised. This runner closes that gap the way the reference's e2e
harness does with docker (test/e2e/runner/perturb.go:43-77): every
node is a SEPARATE OS PROCESS (`python -m tendermint_tpu.cmd start`)
talking TCP p2p, each with its own out-of-process kvstore app over
socket ABCI, and perturbations are REAL signals:

    kill        SIGKILL the node process, restart it (perturb.go:46
                docker kill + up). The app process survives, so the
                restarted node must WAL-replay and ABCI-handshake
                against an app that is ahead of/behind its stores.
    restart     SIGTERM, wait for exit, start again (graceful).
    pause       SIGSTOP ... SIGCONT after a few seconds — the process
                is alive but silent, like a frozen VM.
    disconnect  approximated as a longer SIGSTOP: without container
                network namespaces a Python process can't have its
                sockets severed externally. Honest limitation.

Invariants run over LIVE RPC (test/e2e/tests/ queries its nodes the
same way): height convergence via /status, hash agreement via /block,
tx inclusion under load via /abci_query against the kvstore app. The
block-interval benchmark covers the reference's 100-block window
(benchmark.go:14-34) when asked for.

`state_sync` nodes work across processes: when any node wants state
sync, every app process serves snapshots (`abci kvstore
--snapshot-interval`), and the late joiner's trust root is seeded the
way an operator would — block-1 hash fetched over a live node's RPC
and written into its config before its process starts. The end-of-run
invariant proves a real restore: the node must be at the tip yet
answer "no block at height 1" — a restored node never holds the FULL
genesis block (backfill fetches headers+commits only), while a node
that silently blocksynced from genesis does.

Process-mode limitations (documented, not silent): `misbehaviors`
(the double-prevote hook monkeypatches consensus internals) are
in-process-runner-only; manifests using them are rejected here.
Databases are forced to sqlite — a killed process must find its
stores on disk when it comes back.
"""

from __future__ import annotations

import asyncio
import base64
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ..config import Config, write_config
from ..crypto.ed25519 import PrivKeyEd25519
from ..node import NodeKey
from ..privval import FilePV
from ..rpc.client import HTTPClient, RPCClientError
from ..types.genesis import GenesisDoc, GenesisValidator
from .manifest import Manifest
from .runner import RunReport

__all__ = ["ProcessRunner", "run_manifest_processes"]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _child_env() -> dict:
    """Child processes run CPU-only jax and never touch the device
    tunnel: strip the accelerator plugin's site dir from PYTHONPATH
    and pin JAX_PLATFORMS (same hygiene as tests/conftest.py)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        p
        for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and os.path.basename(p) != ".axon_site"
    )
    # the repo root so `-m tendermint_tpu.cmd` resolves in children
    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = (
        root + (os.pathsep + env["PYTHONPATH"] if env["PYTHONPATH"] else "")
    )
    return env


class _ProcHandle:
    def __init__(self, name: str, cfg: Config):
        self.name = name
        self.cfg = cfg
        self.node_proc: Optional[subprocess.Popen] = None
        self.app_proc: Optional[subprocess.Popen] = None
        self.paused = False
        self.rpc = HTTPClient(cfg.rpc.laddr, timeout=5.0)

    @property
    def live(self) -> bool:
        return (
            self.node_proc is not None
            and self.node_proc.poll() is None
            and not self.paused
        )


class ProcessRunner:
    """Phases mirror runner.Runner; see module docstring."""

    def __init__(
        self, manifest: Manifest, home: str, timeout: float = 300.0
    ):
        for name, spec in manifest.nodes.items():
            if spec.misbehaviors:
                raise ValueError(
                    f"{name}: misbehaviors are only supported by the "
                    "in-process runner (they monkeypatch consensus "
                    "internals)"
                )
        self.m = manifest
        self.home = home
        self.timeout = timeout
        self.handles: Dict[str, _ProcHandle] = {}
        self.report = RunReport()
        self._tx_seq = 0
        self._sent_keys: List[bytes] = []
        self._resume_tasks: List[asyncio.Task] = []
        # runtime-mutable partition shared with every child via
        # TM_TPU_PARTITION_FILE (crypto/faults.py polls it): partition
        # and heal perturbations rewrite this file mid-run. Tracked as
        # a SET of isolated nodes so partitioning a second node
        # composes with (instead of silently healing) the first.
        self._partition_file = os.path.join(home, "partition.spec")
        self._partitioned: set = set()

    # -- setup (reference: setup.go; same genesis/keys as cmd testnet) --

    def setup(self) -> None:
        m = self.m
        privs = {
            name: PrivKeyEd25519.from_seed(
                name.encode().ljust(32, b"\x9e")[:32]
            )
            for name in m.validators
        }
        genesis = GenesisDoc(
            chain_id=m.chain_id,
            genesis_time_ns=time.time_ns(),
            initial_height=m.initial_height,
            validators=[
                GenesisValidator(pub_key=privs[n].pub_key(), power=p)
                for n, p in sorted(m.validators.items())
            ],
        )
        os.makedirs(self.home, exist_ok=True)
        with open(self._partition_file, "w") as f:
            f.write("")  # no partition at boot
        node_ids: Dict[str, str] = {}
        p2p_port: Dict[str, int] = {}
        for name, spec in self.m.sorted_nodes():
            cfg = Config()
            cfg.base.home = os.path.join(self.home, name)
            cfg.base.chain_id = m.chain_id
            cfg.base.mode = spec.mode
            # the moniker is the node's net-fault-plane label — what a
            # partition.spec member names (TCP hosts are all 127.0.0.1
            # here, so only the moniker/node-ID labels can tell the
            # children apart)
            cfg.base.moniker = name
            # stores must survive SIGKILL: force the on-disk backend
            cfg.base.db_backend = "sqlite"
            cfg.base.abci = "socket"
            cfg.base.proxy_app = f"tcp://127.0.0.1:{_free_port()}"
            cfg.consensus.timeout_propose = 2.0
            cfg.consensus.timeout_prevote = 1.0
            cfg.consensus.timeout_precommit = 1.0
            cfg.consensus.timeout_commit = 0.2
            if spec.state_sync:
                cfg.statesync.enable = True
                cfg.statesync.discovery_time = 2.0
                cfg.statesync.chunk_request_timeout = 10.0
                # trust root seeded over live RPC at spawn time
            cfg.rpc.laddr = f"tcp://127.0.0.1:{_free_port()}"
            p2p_port[name] = _free_port()
            cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_port[name]}"
            cfg.ensure_dirs()
            genesis.save_as(cfg.base.path(cfg.base.genesis_file))
            priv = privs.get(name)
            if priv is not None:
                FilePV.from_priv_key(
                    priv,
                    cfg.base.path(cfg.priv_validator.key_file),
                    cfg.base.path(cfg.priv_validator.state_file),
                ).save()
            node_ids[name] = NodeKey.load_or_generate(
                cfg.base.path(cfg.base.node_key_file)
            ).node_id
            self.handles[name] = _ProcHandle(name, cfg)
        for name, h in self.handles.items():
            h.cfg.p2p.persistent_peers = ",".join(
                f"{node_ids[o]}@127.0.0.1:{p2p_port[o]}"
                for o in self.handles
                if o != name
            )
            write_config(
                h.cfg, os.path.join(h.cfg.base.home, "config", "config.toml")
            )

    # -- start (reference: start.go) --

    # snapshots are advertised by every app when anyone will state
    # sync (the reference e2e app's snapshot_interval manifest knob)
    SNAPSHOT_INTERVAL = 2

    def _spawn_app(self, h: _ProcHandle) -> None:
        cmd = [
            sys.executable, "-m", "tendermint_tpu.cmd",
            "abci", "kvstore", "--addr", h.cfg.base.proxy_app,
        ]
        if any(s.state_sync for s in self.m.nodes.values()):
            cmd += ["--snapshot-interval", str(self.SNAPSHOT_INTERVAL)]
        log = open(os.path.join(h.cfg.base.home, "app.log"), "ab")
        h.app_proc = subprocess.Popen(
            cmd, stdout=log, stderr=subprocess.STDOUT, env=_child_env(),
        )
        log.close()

    def _spawn_node(self, h: _ProcHandle) -> None:
        log = open(os.path.join(h.cfg.base.home, "node.log"), "ab")
        env = _child_env()
        # arm the (initially empty) runtime-mutable partition plane in
        # every node child — partition/heal perturbations mutate the
        # shared file and the children re-read it on change
        env["TM_TPU_PARTITION_FILE"] = self._partition_file
        h.node_proc = subprocess.Popen(
            [
                sys.executable, "-m", "tendermint_tpu.cmd",
                "--home", h.cfg.base.home, "start",
            ],
            stdout=log, stderr=subprocess.STDOUT, env=env,
        )
        log.close()
        h.paused = False

    async def _start_node(self, name: str) -> None:
        h = self.handles[name]
        spec = self.m.nodes[name]
        if spec.state_sync and not h.cfg.statesync.trust_hash:
            await self._seed_state_sync_trust(h)
        if h.app_proc is None or h.app_proc.poll() is not None:
            self._spawn_app(h)
        self._spawn_node(h)

    async def _seed_state_sync_trust(self, h: _ProcHandle) -> None:
        """Anchor the late joiner's trust to the live chain the way an
        operator does: block-1 hash over a running node's RPC, written
        into the joiner's config before its process boots (reference:
        the runner passes trust hashes into statesync configs,
        setup.go)."""
        for other in self.handles.values():
            if other is h or not other.live:
                continue
            try:
                res = await other.rpc.call("block", height=1)
                h.cfg.statesync.trust_height = 1
                h.cfg.statesync.trust_hash = res["block_id"]["hash"]
                write_config(
                    h.cfg,
                    os.path.join(
                        h.cfg.base.home, "config", "config.toml"
                    ),
                )
                return
            except Exception:
                continue
        raise RuntimeError(
            f"{h.name}: no live node answered for the state-sync "
            "trust root"
        )

    # -- load over live RPC (reference: load.go) --

    async def _load_loop(self) -> None:
        rate = self.m.load.tx_rate
        if rate <= 0:
            return
        period = 1.0 / rate
        i = 0
        while True:
            await asyncio.sleep(period)
            live = [h for h in self.handles.values() if h.live]
            if not live:
                continue
            h = live[i % len(live)]
            i += 1
            self._tx_seq += 1
            key = f"load-{self._tx_seq}".encode()
            val = os.urandom(
                max(1, self.m.load.tx_size // 2)
            ).hex().encode()
            tx = (key + b"=" + val)[: self.m.load.tx_size]
            try:
                # short cap: a busy/restarting node must not stall the
                # whole load loop for the full client timeout
                await asyncio.wait_for(
                    h.rpc.call(
                        "broadcast_tx_async",
                        tx=base64.b64encode(tx).decode(),
                    ),
                    timeout=1.0,
                )
                self.report.txs_submitted += 1
                self._sent_keys.append(tx.split(b"=", 1)[0])
            except asyncio.TimeoutError:
                # the cancelled call may have left a half-written
                # request on the kept-alive socket; drop it so the
                # next call reconnects cleanly
                try:
                    await h.rpc.close()
                except Exception:
                    pass
            except Exception:
                pass  # node down / restarting: load is best-effort

    # -- perturb with REAL signals (reference: perturb.go:43-77) --

    async def _apply_perturbation(self, name: str, action: str) -> None:
        h = self.handles[name]
        if h.node_proc is None:
            return
        if action == "kill":
            if h.node_proc.poll() is None:
                h.node_proc.send_signal(signal.SIGKILL)
                h.node_proc.wait()
            # immediate restart, like docker kill + up: the node must
            # repair its WAL tail and handshake-replay against the
            # still-running app process
            await self._start_node(name)
        elif action == "restart":
            if h.node_proc.poll() is None:
                h.node_proc.send_signal(signal.SIGTERM)
                try:
                    await asyncio.get_running_loop().run_in_executor(
                        None, h.node_proc.wait, 30
                    )
                except subprocess.TimeoutExpired:
                    # a shutdown wedged past the grace period becomes
                    # a hard kill, like _teardown — never a raw
                    # exception that aborts the whole run
                    h.node_proc.kill()
                    h.node_proc.wait()
            await self._start_node(name)
        elif action == "partition":
            # cut the node from everyone else at the p2p fault plane:
            # its process keeps running and answering RPC, its links
            # drop every frame (unlike `disconnect`'s SIGSTOP
            # approximation, which also freezes RPC)
            self._partitioned.add(name)
            self._write_partition_spec()
        elif action == "heal":
            self._partitioned.discard(name)
            self._write_partition_spec()
        elif action in ("pause", "disconnect"):
            if h.node_proc.poll() is None:
                h.node_proc.send_signal(signal.SIGSTOP)
                h.paused = True

                async def resume(hold: float) -> None:
                    await asyncio.sleep(hold)
                    if h.node_proc and h.node_proc.poll() is None:
                        h.node_proc.send_signal(signal.SIGCONT)
                    h.paused = False

                self._resume_tasks.append(
                    asyncio.get_running_loop().create_task(
                        resume(3.0 if action == "pause" else 8.0)
                    )
                )

    def _write_partition_spec(self) -> None:
        """Render the isolated-node set as partition groups: each
        isolated node is its OWN group (cut from each other too), the
        remainder one connected group. Empty set = healed net."""
        isolated = sorted(self._partitioned)
        rest = [n for n in self.handles if n not in self._partitioned]
        groups = [[n] for n in isolated]
        if isolated and rest:
            groups.append(rest)
        spec = "|".join(",".join(g) for g in groups) if isolated else ""
        with open(self._partition_file, "w") as f:
            f.write(spec)

    # -- orchestration --

    async def _height_of(self, h: _ProcHandle) -> int:
        try:
            res = await h.rpc.call("status")
            return int(res["sync_info"]["latest_block_height"])
        except Exception:
            return -1

    async def _network_height(self) -> int:
        hs = [
            await self._height_of(h)
            for h in self.handles.values()
            if h.live
        ]
        return max((x for x in hs if x >= 0), default=0)

    async def run(self) -> RunReport:
        self.setup()
        try:
            return await self._run_inner()
        finally:
            await self._teardown()

    async def _run_inner(self) -> RunReport:
        for name, spec in self.m.sorted_nodes():
            if spec.start_at == 0:
                await self._start_node(name)
        load_task = asyncio.get_running_loop().create_task(
            self._load_loop()
        )
        pending_starts = {
            name: s.start_at
            for name, s in self.m.sorted_nodes()
            if s.start_at > 0
        }
        schedule: List[tuple] = []
        for name, h in self.handles.items():
            for p in self.m.nodes[name].perturb:
                schedule.append((p.height, name, p.action))
        schedule.sort()

        deadline = time.monotonic() + self.timeout
        try:
            while True:
                if time.monotonic() > deadline:
                    self.report.failures.append(
                        f"timeout before height {self.m.target_height} "
                        f"(at {await self._network_height()})"
                    )
                    break
                await asyncio.sleep(0.5)
                height = await self._network_height()
                for name, at in list(pending_starts.items()):
                    if height >= at:
                        del pending_starts[name]
                        await self._start_node(name)
                while schedule and schedule[0][0] <= height:
                    _, name, action = schedule.pop(0)
                    await self._apply_perturbation(name, action)
                if (
                    height >= self.m.target_height
                    and not pending_starts
                    and not schedule
                ):
                    # a node whose process is alive but mute — RPC not
                    # answering (-1) or SIGSTOP'd (paused) — IS a
                    # laggard: a process that never recovers must hold
                    # the run open until the timeout records it, not
                    # be silently excluded from convergence
                    laggard = False
                    for h in self.handles.values():
                        alive = (
                            h.node_proc is not None
                            and h.node_proc.poll() is None
                        )
                        if alive and (
                            h.paused
                            or await self._height_of(h)
                            < self.m.target_height
                        ):
                            laggard = True
                    if not laggard:
                        break
        finally:
            load_task.cancel()
            # resume tasks are AWAITED, not cancelled: a cancelled
            # resume leaves its node SIGSTOP'd and invisible to the
            # invariant checks below (their holds are bounded <=8 s)
            await asyncio.gather(
                load_task, *self._resume_tasks, return_exceptions=True
            )

        await self._check_invariants()
        await self._benchmark()
        return self.report

    async def _teardown(self) -> None:
        for h in self.handles.values():
            try:
                await h.rpc.close()
            except Exception:
                pass
            for proc, grace in ((h.node_proc, True), (h.app_proc, False)):
                if proc is None or proc.poll() is not None:
                    continue
                proc.send_signal(signal.SIGCONT)  # un-pause if stopped
                proc.send_signal(
                    signal.SIGTERM if grace else signal.SIGKILL
                )
            for proc in (h.node_proc, h.app_proc):
                if proc is not None:
                    try:
                        await asyncio.get_running_loop().run_in_executor(
                            None, proc.wait, 15
                        )
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        proc.wait()

    # -- test over live RPC (reference: test/e2e/tests/) --

    async def _check_invariants(self) -> None:
        rep = self.report
        live = [h for h in self.handles.values() if h.live]
        if not live:
            rep.failures.append("no live nodes at end of run")
            return
        heights = {}
        for h in live:
            hh = await self._height_of(h)
            if hh >= 0:
                heights[h.name] = hh
        if not heights:
            rep.failures.append("no node answered /status at end of run")
            return
        rep.reached_height = min(heights.values())
        for h in live:
            if h.name not in heights:
                # alive but mute: a restarted process that never
                # recovered must fail the run, not vanish from it
                rep.failures.append(
                    f"{h.name} RPC unreachable at end of run"
                )
        if rep.reached_height < self.m.target_height:
            rep.failures.append(
                f"converged height {rep.reached_height} < target "
                f"{self.m.target_height}"
            )
        # one sweep over the reference node's blocks: hash agreement
        # across nodes + committed-tx count under load. The reference
        # must hold full history, so state-sync nodes (no early
        # blocks by design) are never the baseline.
        full_history = [
            h for h in live if not self.m.nodes[h.name].state_sync
        ]
        ref = (full_history or live)[0]
        committed = 0
        for height in range(1, rep.reached_height + 1):
            try:
                want = await ref.rpc.call("block", height=height)
            except Exception:
                continue
            committed += len(want["block"]["txs"] or [])
            for h in live:
                if h is ref:
                    continue
                try:
                    got = await h.rpc.call("block", height=height)
                except Exception:
                    continue
                if got["block_id"]["hash"] != want["block_id"]["hash"]:
                    rep.failures.append(
                        f"fork at height {height}: {h.name} disagrees "
                        f"with {ref.name}"
                    )
        # state-sync nodes must have RESTORED, not blocksynced from
        # genesis: a restored node never holds the FULL genesis block
        # (backfill fetches headers+commits only), while a node that
        # silently blocksynced from height 1 does.
        for name, spec in self.m.nodes.items():
            if not spec.state_sync:
                continue
            h = self.handles[name]
            synced = False
            try:
                res = await h.rpc.call("status")
                if int(res["sync_info"]["latest_block_height"]) >= 1:
                    try:
                        await h.rpc.call("block", height=1)
                        synced = False  # full genesis block on hand
                    except RPCClientError as e:
                        # only a JSON-RPC-level answer ("no block at
                        # height 1", negative error code) proves the
                        # restore; a transport failure proves nothing
                        synced = e.code is not None and e.code < 0
            except Exception:
                pass
            rep.state_synced[name] = synced
            if not synced:
                rep.failures.append(
                    f"{name} was configured for state sync but holds "
                    "the full genesis block (blocksynced instead?) or "
                    "did not answer RPC"
                )
        if self.m.load.tx_rate > 0:
            rep.txs_committed = committed
            if rep.txs_submitted > 0 and committed == 0:
                rep.failures.append("load ran but no txs were committed")
            # the app STATE must contain committed keys, not just the
            # blocks (kvstore semantics over live abci_query) — a
            # state-corrupting app would otherwise pass
            found = 0
            for key in self._sent_keys[:10]:
                try:
                    res = await ref.rpc.call(
                        "abci_query", path="/store", data=key.hex()
                    )
                    if res["response"].get("log") == "exists":
                        found += 1
                except Exception:
                    pass
            if committed > 0 and self._sent_keys and found == 0:
                rep.failures.append(
                    "no submitted kvstore key is queryable in app state"
                )

    # -- benchmark (reference: benchmark.go:14-34, 100-block window) --

    async def _benchmark(self) -> None:
        live = [h for h in self.handles.values() if h.live]
        if not live:
            return
        ref = live[0]
        times: List[int] = []
        for height in range(1, self.report.reached_height + 1):
            try:
                res = await ref.rpc.call("header", height=height)
                times.append(int(res["header"]["time_ns"]))
            except Exception:
                pass
        if len(times) < 2:
            return
        deltas = [(b - a) / 1e9 for a, b in zip(times, times[1:])]
        # the reference benchmark samples a window past startup
        # (benchmark.go:24 skips to an offset); the first couple of
        # intervals here measure process boot + peer dialing, not
        # steady-state consensus. rep.blocks reports what's included.
        if len(deltas) > 10:
            deltas = deltas[2:]
        rep = self.report
        rep.blocks = len(deltas)
        rep.interval_avg = sum(deltas) / len(deltas)
        mean = rep.interval_avg
        rep.interval_stddev = (
            sum((d - mean) ** 2 for d in deltas) / len(deltas)
        ) ** 0.5
        rep.interval_min = min(deltas)
        rep.interval_max = max(deltas)


def run_manifest_processes(
    manifest: Manifest, home: str, timeout: float = 300.0
) -> RunReport:
    """Convenience sync wrapper (the `e2e run --processes` CLI path)."""
    return asyncio.run(
        ProcessRunner(manifest, home, timeout=timeout).run()
    )
