"""Manifest-driven end-to-end testnet harness.

In-process analog of the reference's docker-compose e2e suite
(test/e2e/): TOML manifests describe networks, a runner executes the
setup/start/load/perturb/wait/test/benchmark schedule over real Nodes
on a MemoryNetwork, and a seeded generator permutes manifests for CI.
"""

from .generator import generate
from .manifest import LoadSpec, Manifest, NodeSpec, Perturbation
from .runner import Runner, RunReport, run_manifest

__all__ = [
    "generate",
    "LoadSpec",
    "Manifest",
    "NodeSpec",
    "Perturbation",
    "Runner",
    "RunReport",
    "run_manifest",
]
