"""Testnet manifests: declarative descriptions of e2e networks.

The reference drives its end-to-end suite from TOML manifests
(test/e2e/pkg/manifest.go) that a runner turns into docker-compose
testnets (test/e2e/runner/setup.go). The TPU-native build keeps the
manifest surface but targets the in-process asyncio harness instead of
containers: every node is a real `node.Node` over a MemoryNetwork, so
one pytest process hosts the whole network and fault schedule.

Manifest shape (TOML; all sections optional except validators):

    chain_id = "e2e-net"
    initial_height = 1
    target_height = 6            # run until every live node is here

    [validators]                 # name -> voting power
    validator01 = 10
    validator02 = 10

    [node.validator01]
    mode = "validator"           # validator | full | seed
    database = "memdb"           # memdb | sqlite
    start_at = 0                 # >0: boot only at that network height
    state_sync = false
    perturb = ["kill:4", "disconnect:3", "pause:5", "restart:6"]
    misbehaviors = { double-prevote = 3 }   # action -> height

    [load]
    tx_rate = 5                  # txs/second pushed at random nodes
    tx_size = 64
"""

from __future__ import annotations

try:
    import tomllib
except ImportError:  # Python < 3.11: the config fallback parser reads
    tomllib = None  # the same subset our generator writes
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

__all__ = ["Manifest", "NodeSpec", "LoadSpec", "Perturbation"]

MODES = ("validator", "full", "seed")
# partition/heal are real p2p-level cuts (crypto/faults.py partition
# sets via TM_TPU_PARTITION_FILE — every child polls the shared file),
# unlike `disconnect`'s SIGSTOP approximation: the process keeps
# running and serving RPC while its links drop everything.
PERTURBATIONS = ("kill", "restart", "disconnect", "pause", "partition", "heal")
MISBEHAVIORS = ("double-prevote",)


@dataclass
class Perturbation:
    """A fault applied to one node when the network reaches `height`."""

    action: str
    height: int

    @classmethod
    def parse(cls, s: str) -> "Perturbation":
        action, _, h = s.partition(":")
        if action not in PERTURBATIONS:
            raise ValueError(f"unknown perturbation {action!r}")
        return cls(action=action, height=int(h or 1))


@dataclass
class NodeSpec:
    name: str
    mode: str = "validator"
    database: str = "memdb"
    start_at: int = 0
    state_sync: bool = False
    perturb: List[Perturbation] = field(default_factory=list)
    misbehaviors: Dict[str, int] = field(default_factory=dict)

    def validate(self) -> None:
        if self.mode not in MODES:
            raise ValueError(f"{self.name}: unknown mode {self.mode!r}")
        for m in self.misbehaviors:
            if m not in MISBEHAVIORS:
                raise ValueError(f"{self.name}: unknown misbehavior {m!r}")
        if self.state_sync and self.start_at == 0:
            raise ValueError(
                f"{self.name}: state_sync requires start_at > 0 "
                "(there must be history to sync)"
            )


@dataclass
class LoadSpec:
    tx_rate: float = 0.0
    tx_size: int = 64


@dataclass
class Manifest:
    chain_id: str = "e2e-net"
    initial_height: int = 1
    target_height: int = 5
    validators: Dict[str, int] = field(default_factory=dict)
    nodes: Dict[str, NodeSpec] = field(default_factory=dict)
    load: LoadSpec = field(default_factory=LoadSpec)

    @classmethod
    def parse(cls, data: dict) -> "Manifest":
        m = cls(
            chain_id=data.get("chain_id", "e2e-net"),
            initial_height=int(data.get("initial_height", 1)),
            target_height=int(data.get("target_height", 5)),
            validators={
                k: int(v) for k, v in data.get("validators", {}).items()
            },
        )
        for name, nd in data.get("node", {}).items():
            spec = NodeSpec(
                name=name,
                mode=nd.get(
                    "mode",
                    "validator" if name in m.validators else "full",
                ),
                database=nd.get("database", "memdb"),
                start_at=int(nd.get("start_at", 0)),
                state_sync=bool(nd.get("state_sync", False)),
                perturb=[
                    Perturbation.parse(p) for p in nd.get("perturb", [])
                ],
                misbehaviors={
                    k: int(v)
                    for k, v in nd.get("misbehaviors", {}).items()
                },
            )
            m.nodes[name] = spec
        ld = data.get("load", {})
        m.load = LoadSpec(
            tx_rate=float(ld.get("tx_rate", 0.0)),
            tx_size=int(ld.get("tx_size", 64)),
        )
        m.validate()
        return m

    @classmethod
    def from_toml(cls, path: str) -> "Manifest":
        if tomllib is not None:
            with open(path, "rb") as f:
                return cls.parse(tomllib.load(f))
        from ..config import _parse_toml_subset

        with open(path, encoding="utf-8") as f:
            return cls.parse(_parse_toml_subset(f.read()))

    def validate(self) -> None:
        if not self.validators:
            raise ValueError("manifest needs at least one validator")
        # validators without an explicit node section get a default one
        for name in self.validators:
            self.nodes.setdefault(name, NodeSpec(name=name))
        for name in self.validators:
            if self.nodes[name].mode != "validator":
                raise ValueError(f"{name} has power but is not a validator")
        for spec in self.nodes.values():
            spec.validate()
            # schedules past the target leave pending_starts/perturb
            # queues non-empty, so Runner.run would spin to timeout and
            # report failure even though the chain converged
            if spec.start_at > self.target_height:
                raise ValueError(
                    f"{spec.name}: start_at {spec.start_at} is beyond "
                    f"target_height {self.target_height}"
                )
            for p in spec.perturb:
                if p.height > self.target_height:
                    raise ValueError(
                        f"{spec.name}: perturbation {p.action}:{p.height} "
                        f"is beyond target_height {self.target_height}"
                    )
        live_from_start = [
            s for s in self.nodes.values()
            if s.start_at == 0 and s.mode == "validator"
        ]
        power_up = sum(self.validators[s.name] for s in live_from_start)
        if power_up * 3 <= sum(self.validators.values()) * 2:
            raise ValueError(
                "validators online at genesis hold <=2/3 power; "
                "the network could never start"
            )

    def sorted_nodes(self) -> List[Tuple[str, NodeSpec]]:
        return sorted(self.nodes.items())

    def to_toml(self) -> str:
        """Serialize back to the TOML shape from_toml reads (tomllib is
        read-only, so this is the writer half — kept next to the reader
        so the two halves of the format evolve together)."""
        lines = [
            f'chain_id = "{self.chain_id}"',
            f"initial_height = {self.initial_height}",
            f"target_height = {self.target_height}",
            "",
            "[validators]",
        ]
        for name, power in sorted(self.validators.items()):
            lines.append(f"{name} = {power}")
        for name, spec in self.sorted_nodes():
            lines += [
                "",
                f"[node.{name}]",
                f'mode = "{spec.mode}"',
                f'database = "{spec.database}"',
            ]
            if spec.start_at:
                lines.append(f"start_at = {spec.start_at}")
            if spec.state_sync:
                lines.append("state_sync = true")
            if spec.perturb:
                entries = ", ".join(
                    f'"{p.action}:{p.height}"' for p in spec.perturb
                )
                lines.append(f"perturb = [{entries}]")
            if spec.misbehaviors:
                entries = ", ".join(
                    f"{k} = {v}"
                    for k, v in sorted(spec.misbehaviors.items())
                )
                lines.append(f"misbehaviors = {{ {entries} }}")
        if self.load.tx_rate:
            lines += [
                "",
                "[load]",
                f"tx_rate = {self.load.tx_rate}",
                f"tx_size = {self.load.tx_size}",
            ]
        return "\n".join(lines) + "\n"
