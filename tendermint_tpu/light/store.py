"""Trusted light-block store (reference: light/store/db/db.go).

Persists verified LightBlocks keyed by big-endian height so range scans
iterate in height order, like the reference's lb/<height> keyspace.
"""

from __future__ import annotations

import struct
from typing import Optional

from ..store.kv import KVStore
from ..types.light import LightBlock

__all__ = ["LightStore"]

_PREFIX = b"lb/"


def _key(height: int) -> bytes:
    return _PREFIX + struct.pack(">Q", height)


class LightStore:
    def __init__(self, db: KVStore) -> None:
        self.db = db

    def save_light_block(self, lb: LightBlock) -> None:
        """reference: db.go SaveLightBlock."""
        if lb.height <= 0:
            raise ValueError("light block height must be positive")
        self.db.set(_key(lb.height), lb.to_proto())

    def light_block(self, height: int) -> Optional[LightBlock]:
        raw = self.db.get(_key(height))
        if raw is None:
            return None
        return LightBlock.from_proto(raw)

    def _heights(self) -> list:
        out = []
        for k, _v in self.db.iterate(_PREFIX, _PREFIX + b"\xff"):
            out.append(struct.unpack(">Q", k[len(_PREFIX):])[0])
        return out

    def latest_light_block(self) -> Optional[LightBlock]:
        """reference: db.go LightBlockBefore/latest."""
        heights = self._heights()
        if not heights:
            return None
        return self.light_block(max(heights))

    def first_light_block(self) -> Optional[LightBlock]:
        heights = self._heights()
        if not heights:
            return None
        return self.light_block(min(heights))

    def light_block_before(self, height: int) -> Optional[LightBlock]:
        """Latest stored block with height < `height`
        (reference: db.go LightBlockBefore)."""
        below = [h for h in self._heights() if h < height]
        if not below:
            return None
        return self.light_block(max(below))

    def delete_light_block(self, height: int) -> None:
        self.db.delete(_key(height))

    def prune(self, size: int) -> None:
        """Keep only the newest `size` blocks (reference: db.go Prune)."""
        heights = sorted(self._heights())
        excess = len(heights) - size
        for h in heights[:max(excess, 0)]:
            self.delete_light_block(h)

    def size(self) -> int:
        return len(self._heights())
