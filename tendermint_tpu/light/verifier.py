"""Core light-client verification — the batch-verify showcase.

reference: light/verifier.go (VerifyNonAdjacent :33, VerifyAdjacent
:106, Verify :158, verifyNewHeaderAndVals :174, HeaderExpired :214,
VerifyBackwards :228; DefaultTrustLevel :16).

Both verification modes bottom out in the commit-verification family
(types/validation.py), which dispatches whole commits through the
device BatchVerifier when installed — a 10k-header sync is 10-20k
batched device verifies (BASELINE config 4).

Those paths consult the process-wide verified-signature cache
(crypto.sigcache), which matters here twice over: verify_non_adjacent
checks the SAME commit against two validator sets (the trusted set's
trust-level check, then 2/3 of its own set) — the second pass re-meets
every triple the first pass just proved; and the sequential window
fallback (light/client.py re-verifying per commit after a merged-batch
failure) only re-pays for the actually-bad commit, since the good
commits' triples were cached by the merged attempt.
"""

from __future__ import annotations

from ..types.light import SignedHeader
from ..types.validation import (
    Fraction,
    verify_commit_light,
    verify_commit_light_bulk,
    verify_commit_light_trusting,
)
from ..types.validator import ValidatorSet
from .errors import (
    InvalidHeaderError,
    NewValSetCantBeTrustedError,
    OldHeaderExpiredError,
    VerificationError,
)

__all__ = [
    "DEFAULT_TRUST_LEVEL",
    "MAX_CLOCK_DRIFT_NS",
    "verify",
    "verify_adjacent",
    "verify_adjacent_batch",
    "verify_non_adjacent",
    "verify_backwards",
    "header_expired",
]

# reference: light/verifier.go:16
DEFAULT_TRUST_LEVEL = Fraction(1, 3)
# reference: light/client.go defaultMaxClockDrift (10 s)
MAX_CLOCK_DRIFT_NS = 10 * 1_000_000_000


def header_expired(
    h: SignedHeader, trusting_period_ns: int, now_ns: int
) -> bool:
    """reference: light/verifier.go:214-222."""
    expiration = h.header.time_ns + trusting_period_ns
    return now_ns > expiration


def _validate_trust_level(lvl: Fraction) -> None:
    """Must be in [1/3, 1] (reference: light/verifier.go:251-259)."""
    if (
        lvl.numerator * 3 < lvl.denominator
        or lvl.numerator > lvl.denominator
        or lvl.denominator == 0
    ):
        raise ValueError(f"trust level must be within [1/3, 1], got {lvl}")


def _verify_new_header_and_vals(
    chain_id: str,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusted_header: SignedHeader,
    now_ns: int,
    max_clock_drift_ns: int,
) -> None:
    """reference: light/verifier.go:174-212."""
    try:
        untrusted_header.validate_basic(chain_id)
    except ValueError as e:
        raise InvalidHeaderError(f"untrusted header invalid: {e}") from e
    if untrusted_header.header.height <= trusted_header.header.height:
        raise InvalidHeaderError(
            f"expected new header height {untrusted_header.header.height} "
            f"to be greater than trusted {trusted_header.header.height}"
        )
    if untrusted_header.header.time_ns <= trusted_header.header.time_ns:
        raise InvalidHeaderError(
            "expected new header time after trusted header time"
        )
    if untrusted_header.header.time_ns >= now_ns + max_clock_drift_ns:
        raise InvalidHeaderError(
            "new header time is from the future (beyond clock drift)"
        )
    if (
        untrusted_header.header.validators_hash
        != untrusted_vals.hash()
    ):
        raise InvalidHeaderError(
            "validator set does not match header validators_hash"
        )


def verify_non_adjacent(
    chain_id: str,
    trusted_header: SignedHeader,
    trusted_next_vals: ValidatorSet,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period_ns: int,
    now_ns: int,
    max_clock_drift_ns: int = MAX_CLOCK_DRIFT_NS,
    trust_level: Fraction = DEFAULT_TRUST_LEVEL,
) -> None:
    """Skipping verification: trust-level of the *trusted* set must have
    signed the new header, plus 2/3 of the new header's own set
    (reference: light/verifier.go:33-104).

    Raises NewValSetCantBeTrustedError when the trusting check fails —
    the signal to bisect."""
    if untrusted_header.header.height == trusted_header.header.height + 1:
        raise ValueError("headers must be non-adjacent in height")
    _validate_trust_level(trust_level)
    if header_expired(trusted_header, trusting_period_ns, now_ns):
        raise OldHeaderExpiredError(
            trusted_header.header.time_ns + trusting_period_ns, now_ns
        )
    _verify_new_header_and_vals(
        chain_id, untrusted_header, untrusted_vals, trusted_header,
        now_ns, max_clock_drift_ns,
    )
    # trust-level of the set we trust signed it (batch device verify)
    try:
        verify_commit_light_trusting(
            chain_id,
            trusted_next_vals,
            untrusted_header.commit,
            trust_level,
        )
    except Exception as e:
        raise NewValSetCantBeTrustedError(str(e)) from e
    # 2/3 of its own claimed set signed it (batch device verify)
    try:
        verify_commit_light(
            chain_id,
            untrusted_vals,
            untrusted_header.commit.block_id,
            untrusted_header.header.height,
            untrusted_header.commit,
        )
    except Exception as e:
        raise InvalidHeaderError(str(e)) from e


def adjacent_header_checks(
    chain_id: str,
    trusted_header: SignedHeader,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period_ns: int,
    now_ns: int,
    max_clock_drift_ns: int = MAX_CLOCK_DRIFT_NS,
) -> None:
    """The host-side half of verify_adjacent: every check except the
    commit signature verification. Split out so the light client's
    sequential group path can run all header checks for a window of
    hops first, then verify every commit's signatures in ONE device
    batch (light/client.py _verify_sequential)."""
    if untrusted_header.header.height != trusted_header.header.height + 1:
        raise ValueError("headers must be adjacent in height")
    if header_expired(trusted_header, trusting_period_ns, now_ns):
        raise OldHeaderExpiredError(
            trusted_header.header.time_ns + trusting_period_ns, now_ns
        )
    _verify_new_header_and_vals(
        chain_id, untrusted_header, untrusted_vals, trusted_header,
        now_ns, max_clock_drift_ns,
    )
    if (
        untrusted_header.header.validators_hash
        != trusted_header.header.next_validators_hash
    ):
        raise InvalidHeaderError(
            "header validators_hash does not match trusted header "
            "next_validators_hash"
        )


def verify_adjacent(
    chain_id: str,
    trusted_header: SignedHeader,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period_ns: int,
    now_ns: int,
    max_clock_drift_ns: int = MAX_CLOCK_DRIFT_NS,
) -> None:
    """Sequential verification: the new validator set is pinned by the
    trusted header's next_validators_hash
    (reference: light/verifier.go:106-156)."""
    adjacent_header_checks(
        chain_id, trusted_header, untrusted_header, untrusted_vals,
        trusting_period_ns, now_ns, max_clock_drift_ns,
    )
    try:
        verify_commit_light(
            chain_id,
            untrusted_vals,
            untrusted_header.commit.block_id,
            untrusted_header.header.height,
            untrusted_header.commit,
        )
    except Exception as e:
        raise InvalidHeaderError(str(e)) from e


def verify_adjacent_batch(
    chain_id: str,
    trusted_header: SignedHeader,
    blocks,
    trusting_period_ns: int,
    now_ns: int,
    max_clock_drift_ns: int = MAX_CLOCK_DRIFT_NS,
) -> None:
    """Sequential verification of M height-chained light blocks in ONE
    sigcache-aware call — the bulk form of verify_adjacent and the
    light half of the stateless fleet-serving path.

    `blocks` is an ascending run of LightBlocks starting at
    trusted_header.height + 1. All header-chain checks run first, in
    hop order, with verify_adjacent's exact per-hop errors (the shared
    adjacent_header_checks); every commit's signatures then go through
    verify_commit_light_bulk: a warm fleet pass (a node re-serving
    headers it has verified before) is one commit-memo probe + one
    tally per commit — no sign-bytes encoding, no per-triple cache
    keys, no crypto — and a cold pass is one merged bulk sigcache
    probe + one grouped batch verify for ALL M commits instead of M
    independent verifies. Signature failures surface as
    InvalidHeaderError without hop attribution; callers needing the
    reference's exact failing hop fall back to the per-hop
    verify_adjacent loop (light/client.py's sequential window does)."""
    blocks = list(blocks)
    prev = trusted_header
    rows = []
    for b in blocks:
        adjacent_header_checks(
            chain_id, prev, b.signed_header, b.validator_set,
            trusting_period_ns, now_ns, max_clock_drift_ns,
        )
        rows.append(
            (
                b.validator_set,
                b.signed_header.commit.block_id,
                b.signed_header.header.height,
                b.signed_header.commit,
            )
        )
        prev = b.signed_header
    try:
        verify_commit_light_bulk(chain_id, rows)
    except Exception as e:
        raise InvalidHeaderError(str(e)) from e


def verify(
    chain_id: str,
    trusted_header: SignedHeader,
    trusted_next_vals: ValidatorSet,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period_ns: int,
    now_ns: int,
    max_clock_drift_ns: int = MAX_CLOCK_DRIFT_NS,
    trust_level: Fraction = DEFAULT_TRUST_LEVEL,
) -> None:
    """Dispatch adjacent/non-adjacent (reference: light/verifier.go:158)."""
    if untrusted_header.header.height != trusted_header.header.height + 1:
        verify_non_adjacent(
            chain_id, trusted_header, trusted_next_vals,
            untrusted_header, untrusted_vals,
            trusting_period_ns, now_ns, max_clock_drift_ns, trust_level,
        )
    else:
        verify_adjacent(
            chain_id, trusted_header, untrusted_header, untrusted_vals,
            trusting_period_ns, now_ns, max_clock_drift_ns,
        )


def verify_backwards(
    chain_id: str,
    untrusted_header: SignedHeader,
    trusted_header: SignedHeader,
) -> None:
    """Verify an OLDER header against a trusted newer one by hash
    chaining (reference: light/verifier.go:228-249). No signature check:
    the hash linkage is the proof."""
    try:
        untrusted_header.validate_basic(chain_id)
    except ValueError as e:
        raise InvalidHeaderError(str(e)) from e
    if untrusted_header.header.height >= trusted_header.header.height:
        raise InvalidHeaderError(
            "untrusted header must have a smaller height"
        )
    if untrusted_header.header.time_ns >= trusted_header.header.time_ns:
        raise InvalidHeaderError(
            "untrusted header must have an earlier time"
        )
    if (
        trusted_header.header.last_block_id.hash
        != untrusted_header.header.hash()
    ):
        raise VerificationError(
            f"trusted header last_block_id does not match untrusted "
            f"header hash at height {untrusted_header.header.height}"
        )
