"""Light client — verify headers without executing the chain.

reference: light/client.go (1175 LoC): TrustOptions, initialization
from an operator trust root, sequential + skipping (bisection)
verification, backwards verification, witness cross-checking via the
detector, primary replacement, store pruning.

Every hop bottoms out in batched commit verification, so a long header
sync streams thousands of signature batches through the device seam
(BASELINE config 4: 10k headers @ 150 validators).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import List, Optional

from ..libs.log import get_logger
from ..types.evidence import LightClientAttackEvidence
from ..types.light import LightBlock
from ..types.validation import Fraction
from .errors import (
    DivergenceError,
    InvalidHeaderError,
    LightClientError,
    NewValSetCantBeTrustedError,
    NoWitnessesError,
)
from .provider import Provider
from .store import LightStore
from .verifier import (
    DEFAULT_TRUST_LEVEL,
    MAX_CLOCK_DRIFT_NS,
    header_expired,
    verify,
    verify_adjacent_batch,
    verify_backwards,
)

__all__ = ["Client", "TrustOptions"]

_DEFAULT_PRUNING_SIZE = 1000  # reference: client.go defaultPruningSize

# Cap on hops per merged device batch in sequential sync. 32 hops x
# 150 validators ~ 4.8k signatures — around half a device bucket, big
# enough to amortize dispatch, small enough that one window's fetch
# doesn't stall verification. The effective window is
# min(this, crypto.batch.group_affinity()): affinity is 1 unless an
# accelerator-backed verifier is installed, so CPU-only deployments
# keep the reference's one-hop loop shape.
SEQUENTIAL_BATCH_HOPS = 32


@dataclass
class TrustOptions:
    """Operator-supplied trust root (reference: light/client.go:59-98).
    `period_ns` should be well below the chain's unbonding period."""

    period_ns: int
    height: int
    hash: bytes

    def validate(self) -> None:
        if self.period_ns <= 0:
            raise ValueError("trusting period must be positive")
        if self.height <= 0:
            raise ValueError("trust height must be positive")
        if len(self.hash) != 32:
            raise ValueError("trust hash must be 32 bytes")


class Client:
    """reference: light/client.go Client."""

    def __init__(
        self,
        chain_id: str,
        trust_options: TrustOptions,
        primary: Provider,
        witnesses: List[Provider],
        store: LightStore,
        sequential: bool = False,
        trust_level: Fraction = DEFAULT_TRUST_LEVEL,
        max_clock_drift_ns: int = MAX_CLOCK_DRIFT_NS,
        pruning_size: int = _DEFAULT_PRUNING_SIZE,
    ) -> None:
        trust_options.validate()
        self.chain_id = chain_id
        self.trust_options = trust_options
        self.primary = primary
        self.witnesses = list(witnesses)
        self.store = store
        self.sequential = sequential
        self.trust_level = trust_level
        self.max_clock_drift_ns = max_clock_drift_ns
        self.pruning_size = pruning_size
        self.logger = get_logger("light")
        self._initialized = False

    # ------------------------------------------------------------------
    # setup

    async def initialize(self, now_ns: Optional[int] = None) -> None:
        """Fetch + pin the trust-root light block
        (reference: client.go initializeWithTrustOptions :268-330)."""
        if self._initialized:
            return
        now_ns = now_ns if now_ns is not None else time.time_ns()
        # resume from an existing trusted store when compatible
        existing = self.store.light_block(self.trust_options.height)
        if existing is not None:
            if existing.signed_header.hash() != self.trust_options.hash:
                raise LightClientError(
                    "stored light block at trust height does not match "
                    "the configured trust hash"
                )
            self._initialized = True
            return
        lb = await self._from_primary(self.trust_options.height)
        lb.validate_basic(self.chain_id)
        if lb.signed_header.hash() != self.trust_options.hash:
            raise LightClientError(
                f"trusted header hash mismatch at height "
                f"{self.trust_options.height}: got "
                f"{lb.signed_header.hash().hex()[:16]}, want "
                f"{self.trust_options.hash.hex()[:16]}"
            )
        if header_expired(
            lb.signed_header, self.trust_options.period_ns, now_ns
        ):
            raise LightClientError("trust-root header is already expired")
        self.store.save_light_block(lb)
        self._initialized = True

    # ------------------------------------------------------------------
    # public verification API

    async def verify_light_block_at_height(
        self, height: int, now_ns: Optional[int] = None
    ) -> LightBlock:
        """reference: client.go VerifyLightBlockAtHeight :451-486."""
        await self.initialize(now_ns)
        now_ns = now_ns if now_ns is not None else time.time_ns()
        stored = self.store.light_block(height) if height > 0 else None
        if stored is not None:
            return stored
        latest = self.store.latest_light_block()
        if height == 0 or (latest is not None and height > latest.height):
            return await self._verify_forwards(height, now_ns)
        first = self.store.first_light_block()
        if first is not None and height < first.height:
            return await self._verify_backwards_to(height)
        # between stored blocks: verify forwards from the closest lower
        return await self._verify_forwards(height, now_ns)

    async def update(self, now_ns: Optional[int] = None) -> Optional[LightBlock]:
        """Verify the primary's latest header
        (reference: client.go Update :413-446)."""
        await self.initialize(now_ns)
        now_ns = now_ns if now_ns is not None else time.time_ns()
        latest_primary = await self._from_primary(0)
        latest_trusted = self.store.latest_light_block()
        if (
            latest_trusted is not None
            and latest_primary.height <= latest_trusted.height
        ):
            return None
        return await self._verify_forwards(
            latest_primary.height, now_ns, target=latest_primary
        )

    def trusted_light_block(self, height: int) -> Optional[LightBlock]:
        return self.store.light_block(height)

    # ------------------------------------------------------------------
    # forwards (sequential or skipping)

    async def _verify_forwards(
        self,
        height: int,
        now_ns: int,
        target: Optional[LightBlock] = None,
    ) -> LightBlock:
        trusted = self._closest_trusted_below(height)
        if trusted is None:
            raise LightClientError("no trusted state to verify from")
        if header_expired(
            trusted.signed_header, self.trust_options.period_ns, now_ns
        ):
            raise LightClientError(
                "closest trusted header is outside the trusting period"
            )
        if target is None:
            target = await self._from_primary(height)
            target.validate_basic(self.chain_id)
        if self.sequential:
            verified = await self._verify_sequential(trusted, target, now_ns)
        else:
            verified = await self._verify_skipping(trusted, target, now_ns)
        await self._detect_divergence(verified, now_ns)
        self.store.save_light_block(verified)
        self.store.prune(self.pruning_size)
        return verified

    def _closest_trusted_below(self, height: int) -> Optional[LightBlock]:
        lb = self.store.light_block_before(height + 1)
        return lb

    async def _verify_sequential(
        self, trusted: LightBlock, target: LightBlock, now_ns: int
    ) -> LightBlock:
        """Verify every header between trusted and target
        (reference: client.go verifySequential :488-542), in windows of
        SEQUENTIAL_BATCH_HOPS hops: interim blocks of a window are
        fetched concurrently, all header-chain checks run in hop order
        on host, then every commit's signatures go to the device as ONE
        merged batch (the hop-per-device-call form pays a dispatch per
        header — at 10k headers that is 10k round-trips for work the
        chip finishes in milliseconds). Any window failure falls back
        to the reference's one-hop-at-a-time loop for the exact error
        and store state."""
        from ..crypto.batch import group_affinity

        window = max(1, min(SEQUENTIAL_BATCH_HOPS, group_affinity()))
        if window == 1:
            # no accelerator-backed verifier installed: the reference's
            # one-hop loop, no window machinery, no double-fetch on a
            # verification failure
            cur = trusted
            for h in range(trusted.height + 1, target.height):
                interim = await self._from_primary(h)
                interim.validate_basic(self.chain_id)
                self._verify_hop(cur, interim, now_ns)
                self.store.save_light_block(interim)
                cur = interim
            self._verify_hop(cur, target, now_ns)
            return target
        cur = trusted
        while cur.height < target.height:
            first = cur.height + 1
            last = min(first + window - 1, target.height)
            try:
                chunk = await self._fetch_range(
                    first, min(last, target.height - 1)
                )
                if last == target.height:
                    chunk.append(target)
                for b in chunk:
                    if b.height < target.height:
                        b.validate_basic(self.chain_id)
                # all header-chain checks in hop order, then every
                # commit through ONE sigcache-aware bulk verification
                # (merged probe + grouped batch cold, M memo probes
                # warm — types/validation.verify_commit_light_bulk)
                verify_adjacent_batch(
                    self.chain_id,
                    cur.signed_header,
                    chunk,
                    self.trust_options.period_ns,
                    now_ns,
                    self.max_clock_drift_ns,
                )
            except Exception as e:
                # reference-exact fallback: refetch and verify one hop
                # at a time so the first failing height raises its own
                # error with every prior hop verified and saved. Logged
                # so a systematic batch-path defect (every window
                # falling back, doubling provider load) is visible.
                self.logger.info(
                    "sequential window fell back to per-hop verify",
                    first=first,
                    last=last,
                    err=repr(e),
                )
                for h in range(first, last + 1):
                    if h == target.height:
                        interim = target
                    else:
                        interim = await self._from_primary(h)
                        interim.validate_basic(self.chain_id)
                    self._verify_hop(cur, interim, now_ns)
                    if h < target.height:
                        self.store.save_light_block(interim)
                    cur = interim
                continue
            for b in chunk:
                if b.height < target.height:
                    self.store.save_light_block(b)
            cur = chunk[-1]
        return target

    async def _fetch_range(self, first: int, last: int) -> List[LightBlock]:
        """Fetch heights [first, last] ascending: ONE bulk
        `light_blocks` round-trip from the primary when it serves the
        range (Provider.light_blocks — the rpc bulk route for HTTP
        providers), else the per-height failover fetch with witness
        promotion. A bulk reply with wrong/missing heights is treated
        like a failed fetch, never trusted."""
        import asyncio

        if last < first:
            return []
        try:
            got = list(await self.primary.light_blocks(first, last))
            if [b.height for b in got] == list(range(first, last + 1)):
                return got
            self.logger.info(
                "bulk light_blocks returned wrong heights; refetching",
                primary=self.primary.id(), first=first, last=last,
            )
        except Exception as e:
            self.logger.info(
                "bulk light_blocks fetch failed; per-height fallback",
                primary=self.primary.id(), first=first, last=last,
                err=repr(e),
            )
        # return_exceptions so one failed fetch does not leave the
        # window's other in-flight fetches orphaned (gather would
        # otherwise raise immediately and abandon them)
        fetched = await asyncio.gather(
            *(self._from_primary(h) for h in range(first, last + 1)),
            return_exceptions=True,
        )
        for f in fetched:
            if isinstance(f, BaseException):
                raise f
        return list(fetched)

    async def _verify_skipping(
        self, trusted: LightBlock, target: LightBlock, now_ns: int
    ) -> LightBlock:
        """Bisection (reference: client.go verifySkipping :544-618):
        try the direct non-adjacent hop; when <1/3 of the trusted set
        signed the target, fetch the midpoint and recurse."""
        cache: List[LightBlock] = [target]
        cur = trusted
        while True:
            candidate = cache[-1]
            try:
                self._verify_hop(cur, candidate, now_ns)
            except NewValSetCantBeTrustedError:
                pivot = (cur.height + candidate.height) // 2
                if pivot in (cur.height, candidate.height):
                    raise InvalidHeaderError(
                        "bisection exhausted without trustable hop"
                    )
                pivot_block = await self._from_primary(pivot)
                pivot_block.validate_basic(self.chain_id)
                cache.append(pivot_block)
                continue
            # hop verified
            self.store.save_light_block(candidate)
            cur = candidate
            cache.pop()
            if not cache:
                return cur

    def _verify_hop(
        self, trusted: LightBlock, untrusted: LightBlock, now_ns: int
    ) -> None:
        verify(
            self.chain_id,
            trusted.signed_header,
            trusted.validator_set,
            untrusted.signed_header,
            untrusted.validator_set,
            self.trust_options.period_ns,
            now_ns,
            self.max_clock_drift_ns,
            self.trust_level,
        )

    # ------------------------------------------------------------------
    # backwards

    async def _verify_backwards_to(self, height: int) -> LightBlock:
        """Hash-chain back from the first trusted block
        (reference: client.go backwards :860-900)."""
        cur = self.store.first_light_block()
        assert cur is not None
        for h in range(cur.height - 1, height - 1, -1):
            interim = await self._from_primary(h)
            interim.validate_basic(self.chain_id)
            verify_backwards(
                self.chain_id, interim.signed_header, cur.signed_header
            )
            self.store.save_light_block(interim)
            cur = interim
        return cur

    # ------------------------------------------------------------------
    # detector (reference: light/detector.go)

    async def _detect_divergence(
        self, verified: LightBlock, now_ns: int
    ) -> None:
        """Cross-check the newly verified header against all witnesses.
        A witness that serves a DIFFERENT verifiable header at the same
        height is evidence of a light-client attack; a witness that
        serves garbage is dropped (reference: detector.go
        detectDivergence :28-100)."""
        if not self.witnesses:
            return
        remaining: List[Provider] = []
        evidence: List[LightClientAttackEvidence] = []
        for witness in self.witnesses:
            try:
                w_lb = await witness.light_block(verified.height)
            except Exception:
                # unresponsive witness: keep (transient) — reference
                # drops after repeated failures; we keep it simple
                remaining.append(witness)
                continue
            if (
                w_lb.signed_header.hash()
                == verified.signed_header.hash()
            ):
                remaining.append(witness)
                continue
            # conflicting header: is it *verifiable* from a trusted
            # block STRICTLY below the verified height? (the verified
            # block itself is already stored and must not anchor its
            # own cross-check)
            common = self.store.light_block_before(verified.height)
            try:
                w_lb.validate_basic(self.chain_id)
                self._verify_conflicting(common, w_lb, now_ns)
            except (LightClientError, ValueError):
                self.logger.info(
                    "witness sent invalid conflicting header; removing",
                    witness=witness.id(),
                )
                continue  # drop witness
            ev = LightClientAttackEvidence(
                conflicting_block=w_lb,
                common_height=common.height if common else 0,
                timestamp_ns=w_lb.signed_header.header.time_ns,
            )
            evidence.append(ev)
            remaining.append(witness)
        self.witnesses = remaining
        if not self.witnesses:
            raise NoWitnessesError(
                "all witnesses removed during divergence detection"
            )
        if evidence:
            for provider in [self.primary] + self.witnesses:
                for ev in evidence:
                    try:
                        await provider.report_evidence(ev)
                    except Exception:
                        pass
            raise DivergenceError(
                f"conflicting verifiable header at height "
                f"{verified.height}: possible light-client attack",
                evidence=evidence,
            )

    def _verify_conflicting(
        self, trusted: Optional[LightBlock], w_lb: LightBlock, now_ns: int
    ) -> None:
        if trusted is None:
            raise InvalidHeaderError("no trusted root for cross-check")
        if trusted.height == w_lb.height:
            if trusted.signed_header.hash() != w_lb.signed_header.hash():
                raise InvalidHeaderError("conflicts with trusted root")
            return
        self._verify_hop(trusted, w_lb, now_ns)

    # ------------------------------------------------------------------
    # providers

    async def _from_primary(self, height: int) -> LightBlock:
        """Fetch from the primary; on failure try witnesses and promote
        the first responsive one, demoting the old primary to the back
        of the witness list. The provider set is never shrunk by fetch
        failures — a height nobody can serve yet (e.g. the chain tip's
        commit) must not destroy the client (reference:
        client.go lightBlockFromPrimary + replacePrimaryProvider)."""
        last_err: Optional[Exception] = None
        for provider in [self.primary] + list(self.witnesses):
            try:
                lb = await provider.light_block(height)
            except Exception as e:
                last_err = e
                continue
            if height != 0 and lb.height != height:
                # lying/confused provider: treat as a failed fetch
                last_err = InvalidHeaderError(
                    f"provider {provider.id()} returned height "
                    f"{lb.height}, requested {height}"
                )
                continue
            if provider is not self.primary:
                self.logger.info(
                    "promoting witness to primary",
                    old=self.primary.id(), new=provider.id(),
                )
                self.witnesses = [
                    w for w in self.witnesses if w is not provider
                ] + [self.primary]
                self.primary = provider
            return lb
        raise NoWitnessesError(
            f"no provider could serve height {height}: {last_err}"
        )
