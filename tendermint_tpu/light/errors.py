"""Light client errors (reference: light/errors.go)."""

from __future__ import annotations

__all__ = [
    "LightClientError",
    "OldHeaderExpiredError",
    "NewValSetCantBeTrustedError",
    "InvalidHeaderError",
    "VerificationError",
    "LightBlockNotFoundError",
    "NoWitnessesError",
    "DivergenceError",
]


class LightClientError(Exception):
    pass


class OldHeaderExpiredError(LightClientError):
    """The trusted header is outside the trusting period
    (reference: light/errors.go ErrOldHeaderExpired)."""

    def __init__(self, at_ns: int, now_ns: int) -> None:
        super().__init__(
            f"old header has expired at {at_ns} (now: {now_ns})"
        )
        self.at_ns = at_ns
        self.now_ns = now_ns


class NewValSetCantBeTrustedError(LightClientError):
    """< trust-level of the trusted set signed the new header — the
    caller should bisect (reference: light/errors.go
    ErrNewValSetCantBeTrusted)."""


class InvalidHeaderError(LightClientError):
    """The header failed basic or signature validation — the provider
    is faulty (reference: light/errors.go ErrInvalidHeader)."""


class VerificationError(LightClientError):
    pass


class LightBlockNotFoundError(LightClientError):
    """Provider has no block at the requested height
    (reference: light/provider/errors.go ErrLightBlockNotFound)."""


class NoWitnessesError(LightClientError):
    """All witnesses have been removed — the client cannot cross-check
    and must halt (reference: light/errors.go ErrNoWitnesses)."""


class DivergenceError(LightClientError):
    """A witness provided a conflicting, verifiable header — a possible
    light-client attack; evidence has been reported
    (reference: light/detector.go)."""

    def __init__(self, msg: str, evidence=None) -> None:
        super().__init__(msg)
        self.evidence = evidence or []
