"""Light-block providers (reference: light/provider/provider.go).

A Provider serves LightBlocks for a chain and accepts evidence of
attacks. Implementations here:

- LocalProvider: reads a node's own block/state stores (the reference's
  local RPC provider over a co-located node; used by statesync serving,
  tests, and the light proxy against a trusted full node).
- P2PProvider: fetches over the statesync LightBlock channel via a
  fetch callable (reference: statesync/dispatcher.go + the p2p state
  provider).
- HTTPProvider (rpc client-backed) lives with the RPC package.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Optional

from ..types.light import LightBlock, SignedHeader
from .errors import LightBlockNotFoundError

__all__ = ["Provider", "LocalProvider", "P2PProvider"]


class Provider(ABC):
    """reference: light/provider/provider.go:14-40."""

    @abstractmethod
    def id(self) -> str: ...

    @abstractmethod
    async def light_block(self, height: int) -> LightBlock:
        """Return the light block at height (0 = latest). Raises
        LightBlockNotFoundError when the provider has no such block."""

    async def light_blocks(self, first: int, last: int) -> list:
        """Light blocks for every height in [first, last], ascending —
        the bulk fetch the sequential window sync and fleet serving
        run on. Default: concurrent per-height light_block fetches
        (the window concurrency the client's fetch always had), so
        every provider is bulk-callable; transports with a real bulk
        surface (the rpc `light_blocks` route) override with one
        round trip per page."""
        import asyncio

        # return_exceptions so one failed height does not leave the
        # other in-flight fetches orphaned; the first failure raises
        results = await asyncio.gather(
            *(self.light_block(h) for h in range(first, last + 1)),
            return_exceptions=True,
        )
        for r in results:
            if isinstance(r, BaseException):
                raise r
        return list(results)

    @abstractmethod
    async def report_evidence(self, ev) -> None: ...


class LocalProvider(Provider):
    """Serve light blocks straight from a node's stores."""

    def __init__(self, block_store, state_store, id_: str = "local") -> None:
        self.block_store = block_store
        self.state_store = state_store
        self._id = id_
        self.reported_evidence: list = []

    def id(self) -> str:
        return self._id

    async def light_block(self, height: int) -> LightBlock:
        if height == 0:
            height = self.block_store.height()
        meta = self.block_store.load_block_meta(height)
        commit = self.block_store.load_block_commit(height)
        if commit is None and height == self.block_store.height():
            # tip: the +2/3 commit arrives with block height+1; until
            # then serve the locally seen commit (reference:
            # store.go LoadSeenCommit usage in rpc/core Commit)
            seen = self.block_store.load_seen_commit()
            if seen is not None and seen.height == height:
                commit = seen
        vals = self.state_store.load_validators(height)
        if meta is None or commit is None or vals is None:
            raise LightBlockNotFoundError(f"no light block at {height}")
        return LightBlock(
            signed_header=SignedHeader(header=meta.header, commit=commit),
            validator_set=vals,
        )

    async def report_evidence(self, ev) -> None:
        self.reported_evidence.append(ev)


class HTTPProvider(Provider):
    """Fetch light blocks from a full node's RPC `light_block` route
    (reference: light/provider/http)."""

    def __init__(self, addr: str, timeout: float = 10.0) -> None:
        from ..rpc.client import HTTPClient

        self.addr = addr
        self._client = HTTPClient(addr, timeout=timeout)

    def id(self) -> str:
        return self.addr

    async def light_block(self, height: int) -> LightBlock:
        from ..rpc.client import RPCClientError

        try:
            res = await self._client.call("light_block", height=height)
        except RPCClientError as e:
            raise LightBlockNotFoundError(
                f"{self.addr}: {e}"
            ) from e
        return LightBlock.from_proto(bytes.fromhex(res["light_block"]))

    async def light_blocks(self, first: int, last: int) -> list:
        """One `light_blocks` call per served page (the server clamps
        page size; the loop advances past each clamped page). Replies
        are decoded through the golden-pinned LightBlocksResponse
        codec and height-checked: a server that skips or reorders
        heights is treated as having no block, exactly like a lying
        single-height reply."""
        from ..rpc.client import RPCClientError
        from ..types.light import LightBlocksResponse

        out: list = []
        next_h = first
        while next_h <= last:
            try:
                res = await self._client.call(
                    "light_blocks", min_height=next_h, max_height=last
                )
            except RPCClientError as e:
                raise LightBlockNotFoundError(f"{self.addr}: {e}") from e
            page = LightBlocksResponse.from_proto(
                bytes.fromhex(res["light_blocks"])
            ).light_blocks
            if not page:
                raise LightBlockNotFoundError(
                    f"{self.addr}: empty light_blocks page at {next_h}"
                )
            for lb in page:
                if next_h > last:
                    break  # over-full page: ignore the surplus
                if lb.height != next_h:
                    raise LightBlockNotFoundError(
                        f"{self.addr}: light_blocks page out of order: "
                        f"got {lb.height}, want {next_h}"
                    )
                out.append(lb)
                next_h += 1
        return out

    async def report_evidence(self, ev) -> None:
        try:
            await self._client.call(
                "broadcast_evidence", evidence=ev.to_proto().hex()
            )
        except Exception:
            pass  # best effort, matching the reference's behavior

    async def close(self) -> None:
        await self._client.close()


class P2PProvider(Provider):
    """Fetch light blocks from a peer via an async fetch callable
    (statesync reactor's light-block channel machinery)."""

    def __init__(self, peer_id: str, fetch, report=None) -> None:
        """`fetch(height, peer_id) -> Optional[LightBlock]`;
        `report(ev)` forwards evidence to the evidence reactor."""
        self.peer_id = peer_id
        self._fetch = fetch
        self._report = report

    def id(self) -> str:
        return self.peer_id

    async def light_block(self, height: int) -> LightBlock:
        lb: Optional[LightBlock] = await self._fetch(height, self.peer_id)
        if lb is None:
            raise LightBlockNotFoundError(
                f"peer {self.peer_id[:12]} has no light block at {height}"
            )
        return lb

    async def report_evidence(self, ev) -> None:
        if self._report is not None:
            await self._report(ev)
