"""Light client (reference: light/ — client, verifier, detector,
providers, trusted store)."""

from .client import Client, TrustOptions
from .errors import (
    DivergenceError,
    InvalidHeaderError,
    LightBlockNotFoundError,
    LightClientError,
    NewValSetCantBeTrustedError,
    NoWitnessesError,
    OldHeaderExpiredError,
    VerificationError,
)
from .provider import LocalProvider, P2PProvider, Provider
from .store import LightStore
from .verifier import (
    DEFAULT_TRUST_LEVEL,
    MAX_CLOCK_DRIFT_NS,
    header_expired,
    verify,
    verify_adjacent,
    verify_adjacent_batch,
    verify_backwards,
    verify_non_adjacent,
)

__all__ = [
    "Client",
    "TrustOptions",
    "Provider",
    "LocalProvider",
    "P2PProvider",
    "LightStore",
    "DEFAULT_TRUST_LEVEL",
    "MAX_CLOCK_DRIFT_NS",
    "verify",
    "verify_adjacent",
    "verify_adjacent_batch",
    "verify_non_adjacent",
    "verify_backwards",
    "header_expired",
    "LightClientError",
    "OldHeaderExpiredError",
    "NewValSetCantBeTrustedError",
    "InvalidHeaderError",
    "VerificationError",
    "LightBlockNotFoundError",
    "NoWitnessesError",
    "DivergenceError",
]
