"""Benchmark: ed25519 batch-verify throughput on the attached device.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The metric is sig-verifies/sec/chip (BASELINE.json's primary metric) at
batch 8192. `vs_baseline` is the speedup over this host's CPU
single-verify path (OpenSSL via the `cryptography` wheel) measured in the
same process — the reference publishes no absolute numbers, so the CPU
baseline is measured, matching BASELINE.md's methodology.
"""

from __future__ import annotations

import json
import time

import numpy as np


def _make_batch(n: int, seed: int = 11):
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )
    from cryptography.hazmat.primitives.serialization import (
        Encoding,
        PublicFormat,
    )

    rng = np.random.default_rng(seed)
    pks, msgs, sigs = [], [], []
    # sign with a handful of keys (signing cost isn't what we measure)
    keys = []
    for _ in range(min(n, 64)):
        sk = Ed25519PrivateKey.from_private_bytes(
            rng.integers(0, 256, 32, dtype=np.uint8).tobytes()
        )
        keys.append(
            (sk, sk.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw))
        )
    for i in range(n):
        sk, pk = keys[i % len(keys)]
        msg = rng.integers(0, 256, 64, dtype=np.uint8).tobytes()
        pks.append(pk)
        msgs.append(msg)
        sigs.append(sk.sign(msg))
    return pks, msgs, sigs


def main() -> None:
    from tendermint_tpu.ops.ed25519_kernel import Ed25519Verifier

    n = 8192
    pks, msgs, sigs = _make_batch(n)

    verifier = Ed25519Verifier(bucket_sizes=[n])
    # warm-up: compile + first run
    ok = verifier.verify(pks, msgs, sigs)
    assert bool(ok.all()), "warm-up batch failed to verify"

    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        ok = verifier.verify(pks, msgs, sigs)
    dt = (time.perf_counter() - t0) / reps
    assert bool(ok.all())
    device_sigs_per_sec = n / dt

    # CPU baseline: OpenSSL single verify over a slice, extrapolated
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PublicKey,
    )

    m = 512
    handles = [Ed25519PublicKey.from_public_bytes(pk) for pk in pks[:m]]
    t0 = time.perf_counter()
    for h, msg, sig in zip(handles, msgs[:m], sigs[:m]):
        h.verify(sig, msg)
    cpu_dt = time.perf_counter() - t0
    cpu_sigs_per_sec = m / cpu_dt

    print(
        json.dumps(
            {
                "metric": "ed25519_batch_verify_throughput",
                "value": round(device_sigs_per_sec, 1),
                "unit": "sigs/s/chip",
                "vs_baseline": round(
                    device_sigs_per_sec / cpu_sigs_per_sec, 3
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
